"""tasklint — AST-based static analysis for the runtime's invariants.

The architecture built in PRs 1-3 rests on conventions no type checker
sees: all SQLite I/O runs on dedicated off-loop threads, hot-path
instrumentation must use names declared in ``observability/names.py``,
boolean env knobs must go through ``envflag.env_flag``, and
sidecar-facing paths raise the taxonomy in ``errors.py``. A single
blocking call or typo'd flag silently regresses p99 latency or forks a
metric series — so the rules here turn each convention into a CI
failure.

Entry points:

* ``python -m tasksrunner.analysis`` / ``tasksrunner lint`` — the CLI
  (``make lint``, wired into ``make test``).
* :func:`tasksrunner.analysis.engine.run` — programmatic API used by
  the test suite.

Mechanics (see ``docs/modules/17-static-analysis.md``): a rule registry
(:mod:`.core`), per-file result caching keyed on content+ruleset
(:mod:`.cache`), inline suppressions (``# tasklint: disable=<rule>``),
and a checked-in baseline for grandfathered findings
(:mod:`.baseline`).
"""

from __future__ import annotations

from tasksrunner.analysis.core import RULES, Finding, Rule, register
# Import the rule modules while this package init holds the floor: the
# registration imports run in an order where each dependency (blocking
# tables -> program graph -> dataflow engine) completes before its
# dependents, which makes *direct* imports of any analysis submodule
# (``import tasksrunner.analysis.dataflow``) safe instead of circular.
from tasksrunner.analysis import rules as _rules  # noqa: F401

__all__ = ["RULES", "Finding", "Rule", "register"]
