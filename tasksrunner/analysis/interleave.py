"""Interleave phase — atomic sections and shared-state footprints.

The dataflow phase (PR 11) reasons within one control flow; the bugs
that lose acked writes live *between* awaits, where another task runs
in a check-then-act window. This module partitions every async
function into **atomic sections** — maximal await-free regions: code
between two suspension points runs without any other task interleaving
— and computes the **shared-state footprint** of each section: which
``self`` attributes, module globals, and dict/list elements reachable
from them the section reads in branch conditions and writes. A
location counts as *shared* when some **other** function in the lint
target also writes it (the cross-function writer index reuses the
ProgramGraph's attribute-write records and this module's module-global
scan); single-writer state cannot race and is never reported.

Sections are delimited by ``await`` expressions, ``async for`` loops,
and ``async with`` entries, numbered in the order the walker meets
them; the boundary records which await opened the window, so findings
can say exactly where the interleaving becomes possible. The walk is
source-order — a syntactic under-approximation of execution order —
which keeps it conservative the same way the program phase is: a
reported window is a real pair of a guard and a later write separated
by a real suspension point; absence of a finding is not a proof.

Three *guards* close a window and are recognised here so the rules
don't re-derive them:

* **held asyncio lock** — check and write both execute under the same
  ``async with self._lock:`` (lock attributes are detected exactly
  like the program phase detects ``threading`` locks, from
  ``self.x = asyncio.Lock()`` and module-level assignments);
* **etag threaded** — the write is a call carrying an ``etag``-family
  keyword whose value data-flows from a read in the same function
  invocation (the store re-validates, so the window is benign: the
  stale writer loses the CAS instead of clobbering);
* **epoch compare** — the branch itself is a ``>=``-monotone fence
  comparison; losing the race produces a fenced error, not a lost
  write.

:class:`InterleaveAnalysis` is the facade handed to
``InterleaveRule.check`` — it exposes the per-function
:class:`SectionModel` plus the writer index and the fenced-lane
marker table (``# tasklint: fenced-lane`` on a ``def`` line, scanned
like ``off-loop``).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Iterator

from tasksrunner.analysis.core import FENCED_LANE_RE
from tasksrunner.analysis.program import (
    FunctionInfo,
    ModuleInfo,
    ProgramGraph,
    _resolve_dotted,
    _self_attr,
)

#: asyncio primitives whose instances serialise coroutines — the async
#: twin of program.py's ``_LOCK_FACTORIES``
_ASYNC_LOCK_FACTORIES = {"asyncio.Lock", "asyncio.Condition",
                         "asyncio.Semaphore", "asyncio.BoundedSemaphore"}

#: keyword names that thread a compare-and-swap token into a write
ETAG_KWARGS = frozenset({"etag", "expected_etag", "if_match", "expected"})

#: operand name fragments that identify a fencing counter
EPOCH_NAMES = ("epoch", "term", "generation", "fence")

#: method names that mutate a container in place
_MUTATORS = frozenset({"append", "add", "remove", "discard", "pop",
                       "popleft", "clear", "update", "setdefault",
                       "insert", "extend"})


@dataclasses.dataclass(frozen=True)
class Location:
    """One shared-state location: an attribute of a class (``owner`` =
    class key) or a module global (``owner`` = relpath). Element
    accesses (``self.x[k]``) collapse onto the container — two tasks
    racing on different keys of one dict still race on the dict."""

    kind: str    # "attr" | "global"
    owner: str   # class key ("path::Class") or module relpath
    name: str

    def render(self) -> str:
        if self.kind == "attr":
            return f"self.{self.name}"
        return self.name


@dataclasses.dataclass
class Check:
    """A branch condition reading shared state."""

    loc: Location
    lineno: int
    section: int
    held_locks: frozenset
    monotone_epoch: bool  # the test is a >=-monotone epoch fence


@dataclasses.dataclass
class WriteAccess:
    """A write to shared state (assign, augassign, del, subscript
    store, in-place mutator call, or — for windows only — a call into
    a method that performs the write, recorded in ``via`` as the
    callee's ``file:line``)."""

    loc: Location
    lineno: int
    section: int
    held_locks: frozenset
    etag_threaded: bool  # CAS token from this scope rides the write
    via: str | None = None  # "relpath:line" of the write inside a callee
    #: the write sits in an ``except`` body: it acts on the just-caught
    #: exception (fresh information), not on the stale check
    in_handler: bool = False


@dataclasses.dataclass
class EtagUse:
    """One ``etag=``-family keyword on a call: where the token came
    from. ``origin`` is "read" (awaited read / parameter / fresh
    commit result in this scope), "constant", or "stale" (an attribute
    cached across turns, or an untraceable name)."""

    lineno: int
    section: int
    kwarg: str
    origin: str
    detail: str


@dataclasses.dataclass
class EpochCompare:
    """One comparison whose operand names a fencing counter."""

    lineno: int
    section: int
    monotone: bool
    op: str


@dataclasses.dataclass
class Window:
    """One check-then-act pair: a branch on shared state whose guarded
    region contains a write to the same location in a *later* atomic
    section — at ``open_await`` the function suspended and every other
    task got a chance to invalidate the check."""

    check: Check
    write: WriteAccess
    open_await: int  # lineno of the await that opened the window


class SectionModel:
    """One async function, partitioned. ``boundaries[i]`` is the line
    of the await that *opened* section ``i`` (section 0 has no
    boundary: it starts at the def); ``boundary_reads[i]`` holds the
    shared locations that await's own expression reads."""

    __slots__ = ("fn", "boundaries", "boundary_reads", "checks", "writes",
                 "windows", "etag_uses", "epoch_compares")

    def __init__(self, fn: FunctionInfo):
        self.fn = fn
        self.boundaries: dict[int, int] = {}
        self.boundary_reads: dict[int, frozenset] = {}
        self.checks: list[Check] = []
        self.writes: list[WriteAccess] = []
        self.windows: list[Window] = []
        self.etag_uses: list[EtagUse] = []
        self.epoch_compares: list[EpochCompare] = []

    def opening_await(self, section: int) -> int | None:
        return self.boundaries.get(section)

    def window_joins_checked(self, win: Window) -> bool:
        """True when some await inside the window reads the checked
        location itself — the ``if self._task: ...; await self._task;
        self._task = None`` teardown/join idiom, where the write is the
        release half of joining the object the branch tested, not an
        unrelated act on stale state."""
        for sec in range(win.check.section + 1, win.write.section + 1):
            if win.check.loc in self.boundary_reads.get(sec, ()):
                return True
        return False


#: ``_epoch``, ``f_epoch``, ``leaderTerm`` — an EPOCH_NAMES word at an
#: identifier-token boundary (plain substring would drag in
#: ``terminate`` via ``term``)
_EPOCH_WORD_RE = re.compile(
    r"(?:^|_)(?:%s)(?:_|$)" % "|".join(EPOCH_NAMES))


def _is_epoch_operand(node: ast.AST) -> bool:
    """Does this expression name a fencing counter? Matches attribute /
    name tokens and ``x.get("epoch")``-style dict reads."""
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            name = sub.value
        if name and _EPOCH_WORD_RE.search(
                re.sub(r"(?<=[a-z0-9])(?=[A-Z])", "_", name).lower()):
            return True
    return False


def _early_exit(body: list[ast.stmt]) -> bool:
    """Does this branch body unconditionally leave the enclosing
    suite? ``if seen: return`` / ``continue`` — the *negation* of the
    test dominates everything after the If."""
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _monotone_epoch_test(test: ast.AST) -> bool:
    """True when the test contains a >=/<=/>/< comparison over an
    epoch-named operand: the branch is a monotone fence, losing the
    race is detected, not ignored. Equality tests are *not* monotone —
    they reject legitimately newer epochs and pass corrupt older ones
    symmetrically, so the fencing rules treat them as violations."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Compare) and \
                all(isinstance(op, (ast.Gt, ast.GtE, ast.Lt, ast.LtE))
                    for op in sub.ops):
            operands = [sub.left] + list(sub.comparators)
            if any(_is_epoch_operand(o) for o in operands):
                return True
    return False


class _SectionWalker:
    """Source-order walk of one async function body, tracking the
    section counter, held asyncio locks, and etag-origin bindings."""

    def __init__(self, analysis: "InterleaveAnalysis", mod: ModuleInfo,
                 fn: FunctionInfo):
        self.analysis = analysis
        self.mod = mod
        self.fn = fn
        self.model = SectionModel(fn)
        self.section = 0
        #: allocation counter for section ids — ``section`` rewinds at
        #: branch joins, but every boundary keeps a unique id
        self.next_section = 0
        self.held: list[str] = []
        #: checks whose guarded region the walk is currently inside —
        #: branch bodies, plus (for early-exit guards like ``if k in
        #: self.x: return``) the remainder of the enclosing suite
        self.active_checks: list[Check] = []
        #: nesting depth of ``except`` bodies at the current statement
        self.handler_depth = 0
        #: names whose current value data-flows from a read in this
        #: scope: awaited results, parameters, and projections of both
        self.read_names: set[str] = set()
        args = fn.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            self.read_names.add(a.arg)
        if args.vararg:
            self.read_names.add(args.vararg.arg)
        if args.kwarg:
            self.read_names.add(args.kwarg.arg)
        #: call site line → resolved in-package callees (one level
        #: deep), from the ProgramGraph's edges — lets a window's "act"
        #: live inside a helper the guarded region calls
        self.callees: dict[int, list[FunctionInfo]] = {}
        for edge in fn.edges:
            if edge.dispatch:
                continue
            callee = analysis.graph.functions.get(edge.callee)
            if callee is not None and callee.key != fn.key:
                self.callees.setdefault(edge.lineno, []).append(callee)

    # -- location extraction ----------------------------------------------

    def _loc_of(self, expr: ast.AST) -> Location | None:
        """Shared-state location an expression designates, collapsing
        subscripts and method receivers onto the container."""
        node = expr
        while isinstance(node, ast.Subscript):
            node = node.value
        attr = _self_attr(node)
        if attr is not None and self.fn.cls_key is not None:
            return Location("attr", self.fn.cls_key, attr)
        if isinstance(node, ast.Name) and \
                node.id in self.analysis.module_global_writers(self.mod):
            return Location("global", self.mod.relpath, node.id)
        return None

    def _locs_read(self, test: ast.AST) -> set[Location]:
        """Every shared location a branch condition reads: bare loads,
        ``in`` / ``not in`` membership, ``.get(...)`` reads, and
        comparisons on them."""
        out: set[Location] = set()
        for sub in ast.walk(test):
            if isinstance(sub, (ast.Attribute, ast.Name, ast.Subscript)) \
                    and isinstance(getattr(sub, "ctx", None), ast.Load):
                loc = self._loc_of(sub)
                if loc is not None:
                    out.add(loc)
        return out

    # -- etag origin tracking ---------------------------------------------

    def _value_is_read(self, value: ast.AST | None) -> bool:
        """Does this expression data-flow from a read in this scope?"""
        if value is None:
            return False
        if isinstance(value, ast.Await):
            return True
        if isinstance(value, ast.Name):
            return value.id in self.read_names
        if isinstance(value, ast.Attribute):
            # rec.etag where rec came from a read — but NOT self.x,
            # which is state cached across turns
            if _self_attr(value) is not None:
                return False
            return self._value_is_read(value.value)
        if isinstance(value, ast.Subscript):
            return self._value_is_read(value.value)
        if isinstance(value, ast.Call):
            # item.get("etag"), str(etag), ... — a projection of a read
            func = value.func
            if isinstance(func, ast.Attribute) and \
                    self._value_is_read(func.value):
                return True
            return any(self._value_is_read(a) for a in value.args)
        if isinstance(value, ast.IfExp):
            return self._value_is_read(value.body) or \
                self._value_is_read(value.orelse)
        if isinstance(value, ast.BoolOp):
            return any(self._value_is_read(v) for v in value.values)
        if isinstance(value, ast.Constant) and value.value is None:
            # ``etag = None`` then rebound from the record on the other
            # branch is the unguarded-create idiom — treat the None arm
            # as neutral, the BoolOp/IfExp cases above carry the read
            return False
        return False

    def _bind(self, target: ast.AST, from_read: bool) -> None:
        if isinstance(target, ast.Name):
            if from_read:
                self.read_names.add(target.id)
            else:
                self.read_names.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, from_read)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, from_read)

    # -- the walk -----------------------------------------------------------

    def walk(self) -> SectionModel:
        self._suite(self.fn.node.body)
        return self.model

    def _suite(self, stmts: list[ast.stmt]) -> None:
        """Walk one suite; early-exit guards opened inside it expire
        when it ends (they only dominate the rest of this suite)."""
        mark = len(self.active_checks)
        for child in stmts:
            self._stmt(child)
        del self.active_checks[mark:]

    def _advance(self, lineno: int,
                 reads: ast.AST | list[ast.AST] | None = None) -> None:
        self.next_section += 1
        self.section = self.next_section
        self.model.boundaries[self.section] = lineno
        if reads is not None:
            nodes = reads if isinstance(reads, list) else [reads]
            locs: set[Location] = set()
            for n in nodes:
                locs |= self._locs_read(n)
            self.model.boundary_reads[self.section] = frozenset(locs)

    def _expr(self, node: ast.AST) -> None:
        """Visit an expression: awaits advance the section *after*
        their operand (the operand evaluates before suspending), calls
        get etag/mutator handling."""
        if isinstance(node, ast.Await):
            self._expr(node.value)
            self._advance(node.lineno, reads=node.value)
            return
        if isinstance(node, ast.Call):
            for child in ast.iter_child_nodes(node):
                self._expr(child)
            self._call(node)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # nested scopes partition themselves
        for child in ast.iter_child_nodes(node):
            self._expr(child)
        if isinstance(node, ast.Compare) and \
                any(_is_epoch_operand(o)
                    for o in [node.left] + list(node.comparators)):
            mono = all(isinstance(op, (ast.Gt, ast.GtE, ast.Lt, ast.LtE))
                       for op in node.ops)
            op_name = type(node.ops[0]).__name__ if node.ops else "?"
            self.model.epoch_compares.append(EpochCompare(
                lineno=node.lineno, section=self.section,
                monotone=mono, op=op_name))

    def _call(self, call: ast.Call) -> None:
        held = frozenset(self.held)
        for kw in call.keywords:
            if kw.arg in ETAG_KWARGS:
                if isinstance(kw.value, ast.Constant):
                    origin, detail = "constant", repr(kw.value.value)
                elif self._value_is_read(kw.value):
                    origin, detail = "read", ""
                else:
                    origin = "stale"
                    detail = ast.unparse(kw.value) \
                        if hasattr(ast, "unparse") else ""
                self.model.etag_uses.append(EtagUse(
                    lineno=call.lineno, section=self.section,
                    kwarg=kw.arg, origin=origin, detail=detail))
        # in-place mutation of a shared container: self.x.append(...)
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            loc = self._loc_of(func.value)
            if loc is not None:
                self._add_write(WriteAccess(
                    loc=loc, lineno=call.lineno, section=self.section,
                    held_locks=held, etag_threaded=False,
                    in_handler=self.handler_depth > 0))
        # cross-function act: the guarded region calls a method that
        # writes the checked location (one level deep, via the call
        # graph). A call threading an etag token is CAS-revalidated
        # and closes its own window.
        if self.active_checks and not self._etag_call(call):
            for callee in self.callees.get(call.lineno, ()):
                if callee.cls_key is None:
                    continue
                for w in callee.writes:
                    loc = Location("attr", callee.cls_key, w.attr)
                    if any(chk.loc == loc and chk.section < self.section
                           for chk in self.active_checks):
                        self._pair_windows(WriteAccess(
                            loc=loc, lineno=call.lineno,
                            section=self.section, held_locks=held,
                            etag_threaded=False,
                            via=f"{callee.relpath}:{w.lineno}",
                            in_handler=self.handler_depth > 0))
                        break  # one window per callee is enough

    def _record_write(self, target: ast.AST, lineno: int,
                      etag_threaded: bool) -> None:
        loc = self._loc_of(target)
        if loc is not None:
            self._add_write(WriteAccess(
                loc=loc, lineno=lineno, section=self.section,
                held_locks=frozenset(self.held),
                etag_threaded=etag_threaded,
                in_handler=self.handler_depth > 0))

    def _add_write(self, write: WriteAccess) -> None:
        self.model.writes.append(write)
        self._pair_windows(write)

    def _pair_windows(self, write: WriteAccess) -> None:
        for chk in self.active_checks:
            if chk.loc == write.loc and chk.section < write.section:
                self.model.windows.append(Window(
                    check=chk, write=write,
                    open_await=self.model.boundaries.get(
                        chk.section + 1, write.lineno)))

    def _etag_call(self, value: ast.AST | None) -> bool:
        """Is the (possibly awaited) RHS a call threading an etag
        token? Such a write is CAS-revalidated at the store — a stale
        token loses the swap instead of clobbering, which closes the
        check-then-act window regardless of where the token came from
        (the fenced-lane rules separately police the token's origin)."""
        node = value.value if isinstance(value, ast.Await) else value
        if not isinstance(node, ast.Call):
            return False
        for kw in node.keywords:
            if kw.arg in ETAG_KWARGS:
                if isinstance(kw.value, ast.Constant) and \
                        kw.value.value is None:
                    continue  # etag=None is the unguarded-create form
                return True
        return False

    def _stmt(self, node: ast.stmt) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(node, ast.Assign):
            etag = self._etag_call(node.value)
            self._expr(node.value)
            from_read = self._value_is_read(node.value)
            for tgt in node.targets:
                self._bind(tgt, from_read)
                self._record_write(tgt, node.lineno, etag)
            return
        if isinstance(node, ast.AugAssign):
            self._expr(node.value)
            self._record_write(node.target, node.lineno, False)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                etag = self._etag_call(node.value)
                self._expr(node.value)
                self._bind(node.target, self._value_is_read(node.value))
                self._record_write(node.target, node.lineno, etag)
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                self._record_write(tgt, node.lineno, False)
            return
        if isinstance(node, (ast.If, ast.While)):
            self._expr(node.test)
            locs = self._locs_read(node.test)
            checks: list[Check] = []
            if locs:
                mono = _monotone_epoch_test(node.test)
                held = frozenset(self.held)
                for loc in sorted(locs, key=lambda l: (l.owner, l.name)):
                    checks.append(Check(
                        loc=loc, lineno=node.lineno, section=self.section,
                        held_locks=held, monotone_epoch=mono))
                self.model.checks.extend(checks)
            mark = len(self.active_checks)
            self.active_checks.extend(checks)
            saved = self.section
            self._suite(node.body)
            after_body = self.section
            if isinstance(node, ast.If):
                # the orelse runs when the body does not — it continues
                # from the test's section, not the body's; and an await
                # on an *exiting* branch never suspends the fall-through
                # path, so the join continues from whichever branch
                # falls through (both plain: either may have run and
                # suspended — take the later section, conservative)
                self.section = saved
                self._suite(node.orelse)
                after_orelse = self.section
                body_exits = _early_exit(node.body)
                orelse_exits = bool(node.orelse) and _early_exit(node.orelse)
                if body_exits and not orelse_exits:
                    self.section = after_orelse
                elif orelse_exits and not body_exits:
                    self.section = after_body
                else:
                    self.section = max(after_body, after_orelse)
            else:
                self._suite(node.orelse)
            if not _early_exit(node.body):
                # plain branch: the guard only dominated its own body;
                # an early-exit body (``if seen: return``) dominates
                # the rest of the enclosing suite, so stays active
                del self.active_checks[mark:]
            return
        if isinstance(node, ast.For):
            self._expr(node.iter)
            self._bind(node.target, self._value_is_read(node.iter))
            self._suite(node.body + node.orelse)
            return
        if isinstance(node, ast.AsyncFor):
            self._expr(node.iter)
            self._advance(node.lineno, reads=node.iter)
            self._bind(node.target, True)
            self._suite(node.body + node.orelse)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: list[str] = []
            for item in node.items:
                self._expr(item.context_expr)
                lock = self.analysis.async_lock_id(
                    self.mod, self.fn, item.context_expr)
                if lock is not None:
                    self.held.append(lock)
                    acquired.append(lock)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars,
                               isinstance(node, ast.AsyncWith))
            if isinstance(node, ast.AsyncWith):
                # __aenter__ suspends: entering the block is a boundary
                self._advance(node.lineno,
                              reads=[i.context_expr for i in node.items])
            self._suite(node.body)
            for lock in acquired:
                self.held.remove(lock)
            return
        if isinstance(node, ast.Try):
            self._suite(node.body)
            self.handler_depth += 1
            for handler in node.handlers:
                if handler.name:
                    self.read_names.add(handler.name)
                self._suite(handler.body)
            self.handler_depth -= 1
            self._suite(node.orelse)
            self._suite(node.finalbody)
            return
        if isinstance(node, (ast.Return, ast.Expr, ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(node):
                self._expr(child)
            return
        for child in ast.iter_child_nodes(node):
            self._expr(child)


class InterleaveAnalysis:
    """What an interleave rule sees: the ProgramGraph plus per-async-
    function section models, the cross-function writer index, async
    lock detection, and the fenced-lane marker table."""

    def __init__(self, graph: ProgramGraph):
        self.graph = graph
        self._models: dict[str, SectionModel] = {}
        self._async_lock_attrs: dict[str, set[str]] | None = None
        self._module_async_locks: dict[str, set[str]] | None = None
        self._global_writers: dict[str, dict[str, set[str]]] = {}
        self._attr_writers: dict[Location, set[str]] | None = None
        self._fenced: dict[str, bool] = {}

    # -- section models -----------------------------------------------------

    def model(self, fn: FunctionInfo) -> SectionModel:
        hit = self._models.get(fn.key)
        if hit is None:
            mod = self.graph.modules[fn.relpath]
            hit = _SectionWalker(self, mod, fn).walk()
            self._models[fn.key] = hit
        return hit

    def iter_async_functions(self) -> Iterator[FunctionInfo]:
        for fn in self.graph.iter_functions():
            if fn.is_async:
                yield fn

    def module(self, fn: FunctionInfo) -> ModuleInfo:
        return self.graph.modules[fn.relpath]

    # -- asyncio locks ------------------------------------------------------

    def _scan_async_locks(self) -> None:
        self._async_lock_attrs = {}
        self._module_async_locks = {}
        for mod in self.graph.modules.values():
            mod_locks: set[str] = set()
            for node in mod.tree.body:
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    target = _resolve_dotted(mod.imports, node.value.func)
                    if target in _ASYNC_LOCK_FACTORIES:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                mod_locks.add(tgt.id)
            self._module_async_locks[mod.relpath] = mod_locks
            for cinfo in mod.classes.values():
                attrs: set[str] = set()
                for node in ast.walk(cinfo.node):
                    if isinstance(node, ast.Assign) and \
                            isinstance(node.value, ast.Call):
                        target = _resolve_dotted(mod.imports,
                                                 node.value.func)
                        if target in _ASYNC_LOCK_FACTORIES:
                            for tgt in node.targets:
                                attr = _self_attr(tgt)
                                if attr:
                                    attrs.add(attr)
                self._async_lock_attrs[cinfo.key] = attrs

    def async_lock_id(self, mod: ModuleInfo, fn: FunctionInfo,
                      expr: ast.AST) -> str | None:
        """Canonical id of the asyncio lock an ``async with`` context
        expression designates, or None. ``self._lock.acquire()``-style
        wrappers are not recognised — only the ``async with`` idiom."""
        if self._async_lock_attrs is None:
            self._scan_async_locks()
        # unwrap self.locks[key]-style per-entity locks: the container
        # attribute is the identity (same container → same discipline)
        node = expr
        while isinstance(node, ast.Subscript):
            node = node.value
        attr = _self_attr(node)
        if attr is not None and fn.cls_key is not None:
            if attr in self._async_lock_attrs.get(fn.cls_key, ()):
                return f"{fn.cls_key}.{attr}"
            # self.x.lock where x is a typed attribute of a class with
            # a lock attr — resolve one level through attr_types
            return None
        if isinstance(node, ast.Attribute):
            inner = _self_attr(node.value)
            if inner is not None and fn.cls_key is not None:
                ckey = self.graph._attr_type(
                    self.graph.classes[fn.cls_key], inner)
                if ckey is not None and node.attr in \
                        self._async_lock_attrs.get(ckey, ()):
                    return f"{ckey}.{node.attr}"
        if isinstance(node, ast.Name) and \
                node.id in self._module_async_locks.get(mod.relpath, ()):
            return f"{mod.relpath}::{node.id}"
        return None

    # -- writer indexes -----------------------------------------------------

    def module_global_writers(self, mod: ModuleInfo) -> dict[str, set[str]]:
        """global name → keys of functions that write it (via a
        ``global`` declaration), for one module."""
        hit = self._global_writers.get(mod.relpath)
        if hit is not None:
            return hit
        table: dict[str, set[str]] = {}
        for fn in self.graph.functions.values():
            if fn.relpath != mod.relpath:
                continue
            declared: set[str] = set()
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Global):
                    declared.update(node.names)
            if not declared:
                continue
            for node in ast.walk(fn.node):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for tgt in targets:
                        base = tgt
                        while isinstance(base, ast.Subscript):
                            base = base.value
                        if isinstance(base, ast.Name) and \
                                base.id in declared:
                            table.setdefault(base.id, set()).add(fn.key)
        self._global_writers[mod.relpath] = table
        return table

    def writers(self, loc: Location) -> set[str]:
        """Keys of every function that writes ``loc`` — the rules'
        shared/mutable classifier: a location nobody else writes
        cannot race."""
        if loc.kind == "global":
            mod = self.graph.modules.get(loc.owner)
            if mod is None:
                return set()
            return set(self.module_global_writers(mod).get(loc.name, ()))
        if self._attr_writers is None:
            self._attr_writers = {}
            for fn in self.graph.functions.values():
                if fn.cls_key is None:
                    continue
                for w in fn.writes:
                    key = Location("attr", fn.cls_key, w.attr)
                    self._attr_writers.setdefault(key, set()).add(fn.key)
        return set(self._attr_writers.get(loc, ()))

    def rival_writers(self, fn: FunctionInfo, loc: Location) -> set[str]:
        """Writers of ``loc`` that can actually race with ``fn``:
        everyone but ``fn`` itself and constructors — ``__init__`` /
        ``__post_init__`` writes happen-before any method call on the
        instance, so they never interleave with a window."""
        out = set()
        for key in self.writers(loc) - {fn.key}:
            writer = self.graph.functions.get(key)
            if writer is not None and \
                    writer.name in ("__init__", "__post_init__"):
                continue
            out.add(key)
        return out

    def writer_site(self, fn_key: str, loc: Location) -> int | None:
        """Line of one write to ``loc`` inside ``fn_key``, for chain
        frames."""
        fn = self.graph.functions.get(fn_key)
        if fn is None:
            return None
        for w in fn.writes:
            if w.attr == loc.name:
                return w.lineno
        return None

    # -- fenced lanes -------------------------------------------------------

    def fenced_lane(self, fn: FunctionInfo) -> bool:
        """``# tasklint: fenced-lane`` on the def (or decorator) line —
        scanned like the ``off-loop`` marker."""
        hit = self._fenced.get(fn.key)
        if hit is not None:
            return hit
        mod = self.graph.modules[fn.relpath]
        node = fn.node
        first = min(getattr(node, "lineno", 1),
                    *[d.lineno for d in getattr(node, "decorator_list", [])]
                    or [getattr(node, "lineno", 1)])
        found = False
        for lineno in range(first, getattr(node, "lineno", first) + 1):
            if 0 < lineno <= len(mod.lines) and \
                    FENCED_LANE_RE.search(mod.lines[lineno - 1]):
                found = True
                break
        self._fenced[fn.key] = found
        return found

    # -- chain helpers ------------------------------------------------------

    def frame(self, relpath: str, lineno: int, label: str) -> str:
        """One v4 chain frame: ``file:line [label]``. The suppression
        matcher and the SARIF emitter both strip the trailing label
        before parsing the location."""
        return f"{relpath}:{lineno} [{label}]"
