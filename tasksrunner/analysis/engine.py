"""File walking, suppression handling, baseline plumbing, and the CLI.

Exit codes: 0 clean, 1 findings (or stale-baseline when ``--strict``),
2 usage error. ``--json`` emits one machine-readable document::

    {"version": 1,
     "findings": [{"rule", "path", "line", "col", "message",
                   "fingerprint"}, ...],
     "files": N, "suppressed": N, "baselined": N,
     "stale_baseline": [...]}
"""

from __future__ import annotations

import argparse
import ast
import json
import pathlib
import sys
from typing import Iterable, TextIO

from tasksrunner.analysis import baseline as baseline_mod
from tasksrunner.analysis import rules  # noqa: F401 - populates RULES
from tasksrunner.analysis.cache import ResultCache, ruleset_signature
from tasksrunner.analysis.core import RULES, Finding, SUPPRESS_RE

#: repo root = parent of the tasksrunner package
REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
DEFAULT_TARGET = REPO_ROOT / "tasksrunner"
DEFAULT_BASELINE = REPO_ROOT / "tasklint-baseline.json"
DEFAULT_CACHE = REPO_ROOT / ".tasksrunner" / "tasklint-cache.json"

JSON_VERSION = 1


def relpath(path: pathlib.Path) -> str:
    try:
        return path.resolve().relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return path.resolve().as_posix()


def iter_py_files(paths: Iterable[pathlib.Path]) -> list[pathlib.Path]:
    out: list[pathlib.Path] = []
    for p in paths:
        if p.is_dir():
            out.extend(sorted(f for f in p.rglob("*.py")
                              if "__pycache__" not in f.parts))
        else:
            out.append(p)
    return out


def _suppressions(source: str) -> tuple[dict[int, set[str]], set[str],
                                        list[tuple[int, str]]]:
    """(per-line rule sets, whole-file rule set, unknown-rule sites)."""
    per_line: dict[int, set[str]] = {}
    whole_file: set[str] = set()
    unknown: list[tuple[int, str]] = []
    for lineno, line in enumerate(source.splitlines(), start=1):
        for match in SUPPRESS_RE.finditer(line):
            scope, raw = match.group(1), match.group(2)
            for rid in (r.strip() for r in raw.split(",")):
                if not rid:
                    continue
                if rid not in RULES:
                    unknown.append((lineno, rid))
                elif scope == "disable-file":
                    whole_file.add(rid)
                else:
                    per_line.setdefault(lineno, set()).add(rid)
    return per_line, whole_file, unknown


def lint_file(path: pathlib.Path, rule_ids: tuple[str, ...],
              ) -> tuple[list[Finding], int]:
    """(findings, suppressed-count) for one file — cache-independent."""
    rel = relpath(path)
    try:
        source = path.read_text()
    except (OSError, UnicodeDecodeError) as exc:
        return [Finding(path=rel, line=1, col=1, rule="parse-error",
                        message=f"cannot read file: {exc}")], 0
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:
        return [Finding(path=rel, line=exc.lineno or 1, col=1,
                        rule="parse-error",
                        message=f"syntax error: {exc.msg}")], 0

    from tasksrunner.analysis.core import FileContext
    ctx = FileContext(path, rel, source, tree)
    raw: list[Finding] = []
    for rid in rule_ids:
        raw.extend(RULES[rid].check(ctx))

    per_line, whole_file, unknown = _suppressions(source)
    findings: list[Finding] = []
    suppressed = 0
    for f in raw:
        if f.rule in whole_file or f.rule in per_line.get(f.line, ()):
            suppressed += 1
        else:
            findings.append(f)
    for lineno, rid in unknown:
        # an unknown id in a suppression is itself a finding — a typo
        # here silently re-enables the check it meant to switch off
        findings.append(Finding(
            path=rel, line=lineno, col=1, rule="bad-suppression",
            message=f"unknown rule id {rid!r} in tasklint suppression "
                    f"(known: {', '.join(sorted(RULES))})"))
    return sorted(findings), suppressed


def run(paths: list[pathlib.Path], rule_ids: tuple[str, ...], *,
        baseline_path: pathlib.Path | None = None,
        update_baseline: bool = False,
        cache_path: pathlib.Path | None = None,
        json_out: bool = False,
        out: TextIO = sys.stdout) -> int:
    files = iter_py_files(paths)
    cache = ResultCache(cache_path, ruleset_signature(rule_ids))
    all_findings: list[Finding] = []
    suppressed = 0
    for path in files:
        cached = cache.get(path)
        if cached is not None:
            all_findings.extend(cached)
            continue
        findings, nsup = lint_file(path, rule_ids)
        suppressed += nsup
        cache.put(path, findings)
        all_findings.extend(findings)
    cache.save()
    all_findings.sort()

    base = baseline_mod.load(baseline_path) if baseline_path else {}
    if update_baseline:
        assert baseline_path is not None
        table = baseline_mod.write(baseline_path, all_findings)
        print(f"tasklint: baseline {relpath(baseline_path)} rewritten: "
              f"{len(table)} entries "
              f"({len(all_findings)} findings recorded, stale expired)",
              file=out)
        return 0
    fresh, matched, stale = baseline_mod.apply(all_findings, base)

    if json_out:
        json.dump({
            "version": JSON_VERSION,
            "findings": [f.to_json() for f in fresh],
            "files": len(files),
            "suppressed": suppressed,
            "baselined": matched,
            "stale_baseline": [dict(entry, fingerprint=fp)
                               for fp, entry in sorted(stale.items())],
        }, out, indent=2)
        out.write("\n")
    else:
        for f in fresh:
            print(f.format(), file=out)
        for fp, entry in sorted(stale.items()):
            print(f"tasklint: note: baseline entry {fp} "
                  f"({entry.get('rule')} in {entry.get('path')}) no longer "
                  "matches — run --update-baseline to expire it", file=out)
        status = "FAILED" if fresh else "OK"
        extras = []
        if suppressed:
            extras.append(f"{suppressed} suppressed inline")
        if matched:
            extras.append(f"{matched} baselined")
        if cache.hits:
            extras.append(f"{cache.hits} cached")
        print(f"tasklint {status}: {len(fresh)} finding(s) over "
              f"{len(files)} file(s), {len(rule_ids)} rule(s)"
              + (f" ({', '.join(extras)})" if extras else ""), file=out)
    return 1 if fresh else 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tasksrunner lint",
        description="tasklint: AST checks for the runtime's concurrency, "
                    "env-flag, metric-name, and error-taxonomy invariants.")
    parser.add_argument("paths", nargs="*", type=pathlib.Path,
                        help="files or directories (default: the "
                             "tasksrunner package)")
    parser.add_argument("--rules", default=None, metavar="CSV",
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--json", action="store_true", dest="json_out",
                        help="machine-readable findings on stdout")
    parser.add_argument("--baseline", type=pathlib.Path,
                        default=DEFAULT_BASELINE,
                        help="grandfathered-findings file "
                             "(default: tasklint-baseline.json)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline to the current findings "
                             "(records new, expires stale) and exit 0")
    parser.add_argument("--no-cache", action="store_true",
                        help="ignore and don't write the per-file cache")
    parser.add_argument("--cache", type=pathlib.Path, default=DEFAULT_CACHE,
                        help="cache location (default: "
                             ".tasksrunner/tasklint-cache.json)")
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        width = max(len(r) for r in RULES)
        for rid in sorted(RULES):
            print(f"{rid:<{width}}  {RULES[rid].doc}")
        return 0
    if args.rules:
        rule_ids = tuple(r.strip() for r in args.rules.split(",") if r.strip())
        unknown = [r for r in rule_ids if r not in RULES]
        if unknown:
            print(f"tasklint: unknown rule(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(RULES))})", file=sys.stderr)
            return 2
    else:
        rule_ids = tuple(sorted(RULES))
    paths = args.paths or [DEFAULT_TARGET]
    missing = [p for p in paths if not p.exists()]
    if missing:
        print("tasklint: no such path: "
              + ", ".join(str(p) for p in missing), file=sys.stderr)
        return 2
    return run(paths, rule_ids,
               baseline_path=args.baseline,
               update_baseline=args.update_baseline,
               cache_path=None if args.no_cache else args.cache,
               json_out=args.json_out)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
