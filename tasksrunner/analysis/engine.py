"""File walking, suppression handling, baseline plumbing, and the CLI.

Four phases per run. The **per-file phase** parses each target file
and runs the ``RULES`` table against its AST, exactly as in PR 4. The
**whole-program phase** builds one
:class:`~tasksrunner.analysis.program.ProgramGraph` over the full lint
target and runs the ``PROGRAM_RULES`` table against it — call-graph,
lock-graph, and thread-boundary rules that no single file can express.
The **dataflow phase** reuses the same graph, adds per-function CFGs
and interprocedural taint/escape summaries
(:mod:`~tasksrunner.analysis.dataflow`), and runs the
``DATAFLOW_RULES`` table. The **interleave phase**
(:mod:`~tasksrunner.analysis.interleave`) partitions every async
function into atomic sections and runs the ``INTERLEAVE_RULES`` table
— check-then-act-across-await and fencing-discipline rules over the
section footprints. Whole-tree findings flow through the same
suppression, baseline, and ``--json`` machinery; their extra ``chain``
field lists the path as ``file:line`` frames, optionally labelled
``file:line [role]`` (schema v4) — the suppression matcher and the
SARIF emitter strip the label before parsing the location, so a
``tasklint: disable`` comment on any frame of a labelled chain still
opts out. All whole-tree phases cache under the (content-only) tree
digest, independently, so editing nothing makes warm runs near-free.

Exit codes: 0 clean, 1 findings, 2 usage error. ``--json`` emits one
machine-readable document::

    {"version": 4,
     "findings": [{"rule", "path", "line", "col", "message",
                   "chain", "fingerprint"}, ...],
     "files": N, "suppressed": N, "baselined": N,
     "stale_baseline": [...]}

``--sarif PATH`` additionally writes the post-baseline findings as a
SARIF 2.1.0 document (:mod:`~tasksrunner.analysis.sarif`) for CI
annotation upload; labelled chains become codeFlow steps whose message
carries the label.
"""

from __future__ import annotations

import argparse
import ast
import json
import pathlib
import subprocess
import sys
from typing import Iterable, TextIO

from tasksrunner.analysis import baseline as baseline_mod
from tasksrunner.analysis import rules  # noqa: F401 - populates the tables
from tasksrunner.analysis.cache import (
    DATAFLOW_KEY,
    INTERLEAVE_KEY,
    ResultCache,
    ruleset_signature,
    tree_digest,
)
from tasksrunner.analysis.core import (
    DATAFLOW_RULES,
    INTERLEAVE_RULES,
    PROGRAM_RULES,
    RULES,
    SUPPRESS_RE,
    Finding,
    known_rule_ids,
)
from tasksrunner.analysis.dataflow import DataflowAnalysis
from tasksrunner.analysis.interleave import InterleaveAnalysis
from tasksrunner.analysis.program import ProgramGraph

#: repo root = parent of the tasksrunner package
REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
DEFAULT_TARGET = REPO_ROOT / "tasksrunner"
DEFAULT_BASELINE = REPO_ROOT / "tasklint-baseline.json"
DEFAULT_CACHE = REPO_ROOT / ".tasksrunner" / "tasklint-cache.json"

JSON_VERSION = 4


def relpath(path: pathlib.Path) -> str:
    try:
        return path.resolve().relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return path.resolve().as_posix()


def iter_py_files(paths: Iterable[pathlib.Path]) -> list[pathlib.Path]:
    out: list[pathlib.Path] = []
    for p in paths:
        if p.is_dir():
            out.extend(sorted(f for f in p.rglob("*.py")
                              if "__pycache__" not in f.parts))
        else:
            out.append(p)
    return out


def _suppressions(source: str) -> tuple[dict[int, set[str]], set[str],
                                        list[tuple[int, str]]]:
    """(per-line rule sets, whole-file rule set, unknown-rule sites)."""
    known = known_rule_ids()
    per_line: dict[int, set[str]] = {}
    whole_file: set[str] = set()
    unknown: list[tuple[int, str]] = []
    for lineno, line in enumerate(source.splitlines(), start=1):
        for match in SUPPRESS_RE.finditer(line):
            scope, raw = match.group(1), match.group(2)
            for rid in (r.strip() for r in raw.split(",")):
                if not rid:
                    continue
                if rid not in known:
                    unknown.append((lineno, rid))
                elif scope == "disable-file":
                    whole_file.add(rid)
                else:
                    per_line.setdefault(lineno, set()).add(rid)
    return per_line, whole_file, unknown


def lint_file(path: pathlib.Path, rule_ids: tuple[str, ...],
              ) -> tuple[list[Finding], int]:
    """(findings, suppressed-count) for one file — cache-independent."""
    rel = relpath(path)
    try:
        source = path.read_text()
    except (OSError, UnicodeDecodeError) as exc:
        return [Finding(path=rel, line=1, col=1, rule="parse-error",
                        message=f"cannot read file: {exc}")], 0
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:
        return [Finding(path=rel, line=exc.lineno or 1, col=1,
                        rule="parse-error",
                        message=f"syntax error: {exc.msg}")], 0

    from tasksrunner.analysis.core import FileContext
    ctx = FileContext(path, rel, source, tree)
    raw: list[Finding] = []
    for rid in rule_ids:
        raw.extend(RULES[rid].check(ctx))

    per_line, whole_file, unknown = _suppressions(source)
    findings: list[Finding] = []
    suppressed = 0
    for f in raw:
        if f.rule in whole_file or f.rule in per_line.get(f.line, ()):
            suppressed += 1
        else:
            findings.append(f)
    for lineno, rid in unknown:
        # an unknown id in a suppression is itself a finding — a typo
        # here silently re-enables the check it meant to switch off
        findings.append(Finding(
            path=rel, line=lineno, col=1, rule="bad-suppression",
            message=f"unknown rule id {rid!r} in tasklint suppression "
                    f"(known: {', '.join(sorted(known_rule_ids()))})"))
    return sorted(findings), suppressed


def _frame_location(frame: str) -> tuple[str, int] | None:
    """Parse a chain frame — plain ``file:line`` or the labelled v4
    form ``file:line [role]`` — into (relpath, line)."""
    site = frame.split(" [", 1)[0]
    rel, _, line = site.rpartition(":")
    if rel and line.isdigit():
        return rel, int(line)
    return None


def _program_suppressed(graph: ProgramGraph, finding: Finding) -> bool:
    """A program finding spans locations: honouring a suppression
    comment on the reported line *or on any chain frame* lets either
    the async entry or the offending leaf opt out."""
    if graph.suppressed(finding.path, finding.line, finding.rule):
        return True
    for frame in finding.chain:
        loc = _frame_location(frame)
        if loc is not None and \
                graph.suppressed(loc[0], loc[1], finding.rule):
            return True
    return False


def build_graph(files: list[pathlib.Path]) -> ProgramGraph:
    return ProgramGraph.build([(p, relpath(p)) for p in files])


def lint_program(files: list[pathlib.Path], rule_ids: tuple[str, ...],
                 graph: ProgramGraph | None = None,
                 ) -> tuple[list[Finding], int]:
    """Build the ProgramGraph over ``files`` (or reuse ``graph``) and
    run the whole-program rules. Returns (findings, suppressed)."""
    if graph is None:
        graph = build_graph(files)
    raw: list[Finding] = []
    for rid in rule_ids:
        raw.extend(PROGRAM_RULES[rid].check(graph))
    findings: list[Finding] = []
    suppressed = 0
    for f in raw:
        if _program_suppressed(graph, f):
            suppressed += 1
        else:
            findings.append(f)
    return sorted(findings), suppressed


def lint_dataflow(files: list[pathlib.Path], rule_ids: tuple[str, ...],
                  graph: ProgramGraph | None = None,
                  ) -> tuple[list[Finding], int]:
    """Run the dataflow rules over one DataflowAnalysis (shared CFGs
    and taint/escape summaries). Suppression is chain-aware, exactly
    like the program phase."""
    if graph is None:
        graph = build_graph(files)
    dfa = DataflowAnalysis(graph)
    raw: list[Finding] = []
    for rid in rule_ids:
        raw.extend(DATAFLOW_RULES[rid].check(dfa))
    findings: list[Finding] = []
    suppressed = 0
    for f in raw:
        if _program_suppressed(graph, f):
            suppressed += 1
        else:
            findings.append(f)
    return sorted(findings), suppressed


def lint_interleave(files: list[pathlib.Path], rule_ids: tuple[str, ...],
                    graph: ProgramGraph | None = None,
                    ) -> tuple[list[Finding], int]:
    """Run the interleave rules over one InterleaveAnalysis (atomic
    sections + shared footprints over the same ProgramGraph).
    Suppression is chain-aware and label-tolerant: a disable comment on
    the check, the await, the write, or the rival-writer frame all
    count."""
    if graph is None:
        graph = build_graph(files)
    ia = InterleaveAnalysis(graph)
    raw: list[Finding] = []
    for rid in rule_ids:
        raw.extend(INTERLEAVE_RULES[rid].check(ia))
    findings: list[Finding] = []
    suppressed = 0
    for f in raw:
        if _program_suppressed(graph, f):
            suppressed += 1
        else:
            findings.append(f)
    return sorted(findings), suppressed


def run(paths: list[pathlib.Path], rule_ids: tuple[str, ...], *,
        baseline_path: pathlib.Path | None = None,
        update_baseline: bool = False,
        cache_path: pathlib.Path | None = None,
        json_out: bool = False,
        program_paths: list[pathlib.Path] | None = None,
        sarif_path: pathlib.Path | None = None,
        out: TextIO | None = None) -> int:
    """``paths`` feeds the per-file phase; ``program_paths`` (default:
    the same) feeds the whole-program and dataflow graphs —
    ``--changed`` narrows the former but never the latter, since
    interprocedural rules are only sound over the full tree."""
    if out is None:  # resolved at call time so redirection works
        out = sys.stdout
    files = iter_py_files(paths)
    file_rules = tuple(r for r in rule_ids if r in RULES)
    program_rules = tuple(r for r in rule_ids if r in PROGRAM_RULES)
    dataflow_rules = tuple(r for r in rule_ids if r in DATAFLOW_RULES)
    interleave_rules = tuple(r for r in rule_ids if r in INTERLEAVE_RULES)
    cache = ResultCache(cache_path, ruleset_signature(rule_ids))
    all_findings: list[Finding] = []
    suppressed = 0
    for path in files:
        cached = cache.get(path)
        if cached is not None:
            cfindings, csup = cached
            all_findings.extend(cfindings)
            suppressed += csup
            continue
        findings, nsup = lint_file(path, file_rules)
        suppressed += nsup
        cache.put(path, findings, nsup)
        all_findings.extend(findings)

    if program_rules or dataflow_rules or interleave_rules:
        pfiles = iter_py_files(program_paths) if program_paths is not None \
            else files
        tree_hash = tree_digest(pfiles)
        graph: ProgramGraph | None = None  # built once, shared by all

        if program_rules:
            cached_prog = cache.get_program(tree_hash)
            if cached_prog is not None:
                pfindings, psup = cached_prog
            else:
                graph = graph or build_graph(pfiles)
                pfindings, psup = lint_program(pfiles, program_rules, graph)
                cache.put_program(tree_hash, pfindings, psup)
            all_findings.extend(pfindings)
            suppressed += psup

        if dataflow_rules:
            cached_flow = cache.get_program(tree_hash, key=DATAFLOW_KEY)
            if cached_flow is not None:
                dfindings, dsup = cached_flow
            else:
                graph = graph or build_graph(pfiles)
                dfindings, dsup = lint_dataflow(pfiles, dataflow_rules,
                                                graph)
                cache.put_program(tree_hash, dfindings, dsup,
                                  key=DATAFLOW_KEY)
            all_findings.extend(dfindings)
            suppressed += dsup

        if interleave_rules:
            cached_il = cache.get_program(tree_hash, key=INTERLEAVE_KEY)
            if cached_il is not None:
                ifindings, isup = cached_il
            else:
                graph = graph or build_graph(pfiles)
                ifindings, isup = lint_interleave(pfiles, interleave_rules,
                                                  graph)
                cache.put_program(tree_hash, ifindings, isup,
                                  key=INTERLEAVE_KEY)
            all_findings.extend(ifindings)
            suppressed += isup

    cache.save()
    all_findings.sort()

    base = baseline_mod.load(baseline_path) if baseline_path else {}
    if update_baseline:
        assert baseline_path is not None
        table = baseline_mod.write(baseline_path, all_findings)
        print(f"tasklint: baseline {relpath(baseline_path)} rewritten: "
              f"{len(table)} entries "
              f"({len(all_findings)} findings recorded, stale expired)",
              file=out)
        return 0
    fresh, matched, stale = baseline_mod.apply(all_findings, base)

    if sarif_path is not None:
        from tasksrunner.analysis.sarif import to_sarif
        table: dict = {}
        table.update(RULES)
        table.update(PROGRAM_RULES)
        table.update(DATAFLOW_RULES)
        table.update(INTERLEAVE_RULES)
        docs = {rid: table[rid].doc for rid in rule_ids if rid in table}
        sarif_path.parent.mkdir(parents=True, exist_ok=True)
        sarif_path.write_text(
            json.dumps(to_sarif(fresh, docs), indent=2) + "\n")

    if json_out:
        json.dump({
            "version": JSON_VERSION,
            "findings": [f.to_json() for f in fresh],
            "files": len(files),
            "suppressed": suppressed,
            "baselined": matched,
            "stale_baseline": [dict(entry, fingerprint=fp)
                               for fp, entry in sorted(stale.items())],
        }, out, indent=2)
        out.write("\n")
    else:
        for f in fresh:
            print(f.format(), file=out)
        for fp, entry in sorted(stale.items()):
            print(f"tasklint: note: baseline entry {fp} "
                  f"({entry.get('rule')} in {entry.get('path')}) no longer "
                  "matches — run --update-baseline to expire it", file=out)
        status = "FAILED" if fresh else "OK"
        extras = []
        if suppressed:
            extras.append(f"{suppressed} suppressed inline")
        if matched:
            extras.append(f"{matched} baselined")
        if cache.hits:
            extras.append(f"{cache.hits} cached")
        print(f"tasklint {status}: {len(fresh)} finding(s) over "
              f"{len(files)} file(s), {len(rule_ids)} rule(s)"
              + (f" ({', '.join(extras)})" if extras else ""), file=out)
    return 1 if fresh else 0


def _git(args: list[str]) -> subprocess.CompletedProcess | None:
    try:
        return subprocess.run(["git", "-C", str(REPO_ROOT)] + args,
                              capture_output=True, text=True, timeout=15)
    except (OSError, subprocess.TimeoutExpired):
        return None


def changed_paths(scope: list[pathlib.Path]) -> list[pathlib.Path] | None:
    """Python files changed vs the merge-base with the main branch
    (committed, staged, unstaged, and untracked), restricted to
    ``scope``. None = git unavailable; caller falls back to a full
    lint."""
    base = None
    for ref in ("origin/main", "main"):
        proc = _git(["merge-base", "HEAD", ref])
        if proc is not None and proc.returncode == 0:
            base = proc.stdout.strip()
            break
    diff_ref = base or "HEAD"
    proc = _git(["diff", "--name-only", diff_ref, "--"])
    if proc is None or proc.returncode != 0:
        return None
    names = {line for line in proc.stdout.splitlines() if line}
    others = _git(["ls-files", "--others", "--exclude-standard"])
    if others is not None and others.returncode == 0:
        names |= {line for line in others.stdout.splitlines() if line}
    roots = [p.resolve() for p in scope]
    out: list[pathlib.Path] = []
    for name in sorted(names):
        if not name.endswith(".py"):
            continue
        path = (REPO_ROOT / name).resolve()
        if not path.is_file():
            continue  # deleted since the merge-base
        if any(path == root or root in path.parents for root in roots):
            out.append(path)
    return out


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tasksrunner lint",
        description="tasklint: per-file AST checks plus whole-program "
                    "call-graph, lock-graph, and thread-boundary rules "
                    "for the runtime's concurrency invariants.")
    parser.add_argument("paths", nargs="*", type=pathlib.Path,
                        help="files or directories (default: the "
                             "tasksrunner package)")
    parser.add_argument("--rules", default=None, metavar="CSV",
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--changed", action="store_true",
                        help="per-file phase only lints files changed vs "
                             "the git merge-base with main; the "
                             "whole-program phase still covers the full "
                             "target (cached, so warm runs are cheap)")
    parser.add_argument("--json", action="store_true", dest="json_out",
                        help="machine-readable findings on stdout")
    parser.add_argument("--sarif", type=pathlib.Path, default=None,
                        metavar="PATH",
                        help="also write post-baseline findings as a "
                             "SARIF 2.1.0 document (for CI annotations)")
    parser.add_argument("--baseline", type=pathlib.Path,
                        default=DEFAULT_BASELINE,
                        help="grandfathered-findings file "
                             "(default: tasklint-baseline.json)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline to the current findings "
                             "(records new, expires stale) and exit 0")
    parser.add_argument("--no-cache", action="store_true",
                        help="ignore and don't write the per-file cache")
    parser.add_argument("--cache", type=pathlib.Path, default=DEFAULT_CACHE,
                        help="cache location (default: "
                             ".tasksrunner/tasklint-cache.json)")
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    known = known_rule_ids()
    if args.list_rules:
        table = dict(RULES)
        table.update(PROGRAM_RULES)
        table.update(DATAFLOW_RULES)
        table.update(INTERLEAVE_RULES)
        width = max(len(r) for r in table)
        for rid in sorted(table):
            kind = "program" if rid in PROGRAM_RULES else \
                "dataflow" if rid in DATAFLOW_RULES else \
                "interleave" if rid in INTERLEAVE_RULES else "file"
            print(f"{rid:<{width}}  [{kind}] {table[rid].doc}")
        return 0
    if args.rules:
        rule_ids = tuple(r.strip() for r in args.rules.split(",") if r.strip())
        unknown = [r for r in rule_ids if r not in known]
        if unknown:
            print(f"tasklint: unknown rule(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2
    else:
        rule_ids = tuple(sorted(known))
    paths = args.paths or [DEFAULT_TARGET]
    missing = [p for p in paths if not p.exists()]
    if missing:
        print("tasklint: no such path: "
              + ", ".join(str(p) for p in missing), file=sys.stderr)
        return 2
    program_paths = None
    if args.changed:
        narrowed = changed_paths(paths)
        if narrowed is None:
            print("tasklint: --changed: git unavailable, linting "
                  "everything", file=sys.stderr)
        else:
            program_paths = paths  # program phase stays whole-tree
            paths = narrowed
    return run(paths, rule_ids,
               baseline_path=args.baseline,
               update_baseline=args.update_baseline,
               cache_path=None if args.no_cache else args.cache,
               json_out=args.json_out,
               program_paths=program_paths,
               sarif_path=args.sarif)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
