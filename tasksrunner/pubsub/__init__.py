from tasksrunner.pubsub.base import Message, PubSubBroker, Subscription
from tasksrunner.pubsub.memory import InMemoryBroker
from tasksrunner.pubsub.redis import RedisStreamsBroker
from tasksrunner.pubsub.sqlite import SqliteBroker

__all__ = [
    "Message",
    "PubSubBroker",
    "Subscription",
    "InMemoryBroker",
    "RedisStreamsBroker",
    "SqliteBroker",
]
