"""Pub/sub building-block interface.

Semantics replicated from the reference (SURVEY.md §2.4, §5.8):

* topic-based fan-out: every *consumer group* (≙ a Service Bus
  subscription, named after the consuming app-id —
  bicep/modules/service-bus.bicep:55-57) receives each message;
* **competing consumers** within one group: replicas of the same app
  share the group, each message goes to exactly one of them;
* **at-least-once** delivery: a handler outcome of "nack" (non-2xx in
  the reference, docs/aca/05-aca-dapr-pubsubapi, §3.4 ack contract)
  makes the message visible again for redelivery;
* durability is broker-dependent: groups outlive their subscribers, so
  consumers need not be up when messages arrive
  (docs/aca/05-aca-dapr-pubsubapi/index.md:27-29) — the sqlite broker
  honors this, the in-memory broker only within its process.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable


@dataclass
class Message:
    id: str
    topic: str
    data: Any
    #: transport metadata (content-type etc.)
    metadata: dict[str, str] = field(default_factory=dict)
    #: delivery attempt counter, 1-based
    attempt: int = 1


class Nack:
    """A falsy handler outcome carrying redelivery hints.

    A bare ``False`` tells the broker *that* delivery failed; a
    ``Nack`` also tells it *when to try again* (``retry_after``
    seconds instead of the broker's fixed ``retry_delay``) and whether
    the try should count against the bounded-attempt budget at all.
    ``counts_attempt=False`` is for deliveries the app never processed
    — a 503 during model warmup, a 429 admission shed — where burning
    attempts would dead-letter messages the consumer merely asked to
    see later. ``__bool__`` is ``False`` so brokers that only know the
    ack contract (``if not ok: redeliver``) keep working unchanged.
    """

    __slots__ = ("retry_after", "counts_attempt")

    def __init__(self, retry_after: float | None = None, *,
                 counts_attempt: bool = True) -> None:
        self.retry_after = retry_after
        self.counts_attempt = counts_attempt

    def __bool__(self) -> bool:
        return False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Nack(retry_after={self.retry_after!r}, "
                f"counts_attempt={self.counts_attempt!r})")


def retry_after_from_headers(headers: dict[str, str] | None) -> float | None:
    """Numeric ``Retry-After`` from a response header map (any case),
    or None. HTTP-date forms are ignored — every producer in this
    codebase emits seconds."""
    for key, value in (headers or {}).items():
        if key.lower() == "retry-after":
            try:
                return max(0.0, float(value.strip()))
            except (TypeError, ValueError):
                return None
    return None


#: Handler returns True to ack; False — or a :class:`Nack` carrying
#: redelivery hints — to nack (→ redelivery). A raised exception
#: counts as nack.
Handler = Callable[[Message], Awaitable["bool | Nack"]]


@dataclass
class Subscription:
    topic: str
    group: str
    _cancel: Callable[[], Awaitable[None]] | None = None

    async def cancel(self) -> None:
        if self._cancel is not None:
            await self._cancel()
            self._cancel = None


class PubSubBroker(abc.ABC):
    def __init__(self, name: str):
        self.name = name

    @abc.abstractmethod
    async def publish(self, topic: str, data: Any, *, metadata: dict[str, str] | None = None) -> str:
        """Publish; returns the message id."""

    @abc.abstractmethod
    async def subscribe(self, topic: str, group: str, handler: Handler) -> Subscription:
        """Register ``handler`` as one competing consumer in ``group``."""

    @abc.abstractmethod
    async def ensure_group(self, topic: str, group: str) -> None:
        """Create the durable group without attaching a consumer (≙ the
        Bicep-provisioned Service Bus subscription existing before the
        app ever runs)."""

    async def aclose(self) -> None:  # pragma: no cover - default no-op
        pass
