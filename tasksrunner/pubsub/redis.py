"""Redis Streams pub/sub broker (``pubsub.redis``).

Parity slot: components/dapr-pubsub-redis.yaml:1-12 — the unscoped
local broker that stands in for Service Bus during dev (taught in
docs/aca/05-aca-dapr-pubsubapi; Dapr's redis pub/sub is itself built
on Streams + consumer groups). Contract honored, matching
tasksrunner/pubsub/base.py:

* one stream per topic; one consumer group per subscribing app-id
  (≙ the Service Bus subscription named after the app,
  bicep/modules/service-bus.bicep:55-57);
* competing consumers: replicas share the group via XREADGROUP ``>``;
* at-least-once: a nack leaves the entry in the group's pending list;
  a reclaim loop XPENDINGs entries idle past ``redeliverInterval`` and
  XCLAIMs them for another attempt, carrying the server-side delivery
  count into ``Message.attempt``;
* durable groups: ``ensure_group`` XGROUP-CREATEs at id 0 before any
  consumer exists, so messages published while the app is down are
  delivered on startup (docs/aca/05-aca-dapr-pubsubapi/index.md:27-29);
* poison messages: past ``maxRetries`` attempts the entry is acked out
  of the group and parked on ``<stream>:dead`` for inspection.
"""

from __future__ import annotations

import asyncio
import json
import logging
import uuid
from typing import Any

from tasksrunner.component.registry import driver
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import PubSubError
from tasksrunner.pubsub.base import Handler, Message, PubSubBroker, Subscription
from tasksrunner.redisproto import (
    RedisClient,
    RedisConnection,
    RedisReplyError,
    as_str,
)

logger = logging.getLogger(__name__)

_STREAM_PREFIX = "tasksrunner:topic:"


class RedisStreamsBroker(PubSubBroker):
    def __init__(self, name: str, host: str, *,
                 max_attempts: int = 3,
                 redeliver_interval: float = 0.5,
                 block_ms: int = 200,
                 max_stream_len: int = 10_000):
        super().__init__(name)
        self.client = RedisClient(host)
        self.max_attempts = max_attempts
        self.redeliver_interval = redeliver_interval
        self.block_ms = block_ms
        #: approximate MAXLEN cap per stream — acked entries never
        #: leave the stream otherwise, so an uncapped XADD grows until
        #: the server's maxmemory (same reason Dapr's redis pubsub trims)
        self.max_stream_len = max_stream_len
        self._tasks: list[asyncio.Task] = []
        self._closed = False

    @staticmethod
    def _stream(topic: str) -> str:
        return _STREAM_PREFIX + topic

    # -- PubSubBroker API

    async def publish(self, topic: str, data: Any, *,
                      metadata: dict[str, str] | None = None) -> str:
        entry_id = await self.client.execute(
            "XADD", self._stream(topic),
            "MAXLEN", "~", self.max_stream_len, "*",
            "data", json.dumps(data),
            "metadata", json.dumps(metadata or {}))
        return as_str(entry_id)

    async def ensure_group(self, topic: str, group: str) -> None:
        try:
            await self.client.execute(
                "XGROUP", "CREATE", self._stream(topic), group, "0", "MKSTREAM")
        except RedisReplyError as exc:
            if exc.code != "BUSYGROUP":
                raise PubSubError(
                    f"{self.name}: cannot create group {group!r} "
                    f"on {topic!r}: {exc}") from exc

    async def subscribe(self, topic: str, group: str, handler: Handler) -> Subscription:
        if self._closed:
            raise PubSubError(f"broker {self.name!r} is closed")
        await self.ensure_group(topic, group)
        consumer = uuid.uuid4().hex[:12]
        read_task = asyncio.create_task(
            self._read_loop(topic, group, consumer, handler),
            name=f"redis-read:{topic}:{group}")
        reclaim_task = asyncio.create_task(
            self._reclaim_loop(topic, group, consumer, handler),
            name=f"redis-reclaim:{topic}:{group}")
        self._tasks += [read_task, reclaim_task]

        async def cancel() -> None:
            for task in (read_task, reclaim_task):
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
                if task in self._tasks:
                    self._tasks.remove(task)

        return Subscription(topic=topic, group=group, _cancel=cancel)

    async def aclose(self) -> None:
        self._closed = True
        for task in self._tasks:
            task.cancel()
        for task in self._tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        await self.client.aclose()

    # -- delivery machinery

    def _to_message(self, topic: str, entry_id: str, fields: list, *,
                    attempt: int) -> Message:
        kv = {as_str(fields[i]): as_str(fields[i + 1])
              for i in range(0, len(fields) - 1, 2)}
        return Message(
            id=entry_id,
            topic=topic,
            data=json.loads(kv.get("data", "null")),
            metadata=json.loads(kv.get("metadata", "{}")),
            attempt=attempt,
        )

    async def _read_loop(self, topic: str, group: str, consumer: str,
                         handler: Handler) -> None:
        # A blocked XREADGROUP parks this socket for up to block_ms at a
        # time, so the loop owns a DEDICATED connection — pooled sockets
        # stay free for publish/ack even with many subscriptions.
        stream = self._stream(topic)
        conn = RedisConnection(self.client.host, self.client.port)
        try:
            while True:
                try:
                    reply = await conn.execute(
                        "XREADGROUP", "GROUP", group, consumer,
                        "COUNT", 16, "BLOCK", self.block_ms,
                        "STREAMS", stream, ">")
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    logger.warning("broker %s read loop error: %s",
                                   self.name, exc)
                    conn.close_now()  # reconnects on next execute
                    await asyncio.sleep(self.redeliver_interval)
                    continue
                if not reply:
                    continue
                for _, entries in reply:
                    for raw_id, fields in entries:
                        msg = self._to_message(
                            topic, as_str(raw_id), fields, attempt=1)
                        await self._deliver(stream, group, msg, handler)
        finally:
            conn.close_now()

    async def _deliver(self, stream: str, group: str, msg: Message,
                       handler: Handler) -> None:
        """Run the handler and settle the entry. Never raises (except
        cancellation): a redis hiccup while acking/parking just leaves
        the entry pending, and the reclaim loop redelivers it — the
        at-least-once contract holds either way."""
        try:
            ok = await handler(msg)
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            logger.warning("broker %s: handler raised on %s: %s",
                           self.name, msg.id, exc)
            ok = False
        try:
            if ok:
                await self.client.execute("XACK", stream, group, msg.id)
            elif msg.attempt >= self.max_attempts:
                logger.warning(
                    "broker %s: message %s on %s exhausted %d attempts; "
                    "parking on dead-letter", self.name, msg.id, msg.topic,
                    msg.attempt)
                await self.client.execute(
                    "XADD", stream + ":dead",
                    "MAXLEN", "~", self.max_stream_len, "*",
                    "data", json.dumps(msg.data),
                    "metadata", json.dumps(msg.metadata),
                    "origin_id", msg.id, "group", group,
                    "attempts", str(msg.attempt))
                await self.client.execute("XACK", stream, group, msg.id)
            # else: stays pending for the reclaim loop
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            logger.warning(
                "broker %s: could not settle %s on %s (%s); entry stays "
                "pending for redelivery", self.name, msg.id, msg.topic, exc)

    async def _reclaim_loop(self, topic: str, group: str, consumer: str,
                            handler: Handler) -> None:
        stream = self._stream(topic)
        idle_ms = int(self.redeliver_interval * 1000)
        while True:
            await asyncio.sleep(self.redeliver_interval)
            try:
                rows = await self.client.execute(
                    "XPENDING", stream, group, "IDLE", idle_ms, "-", "+", 32)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                logger.warning("broker %s reclaim error: %s", self.name, exc)
                continue
            for row in rows or []:
                entry_id, delivery_count = as_str(row[0]), int(row[3])
                try:
                    claimed = await self.client.execute(
                        "XCLAIM", stream, group, consumer, idle_ms, entry_id)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    logger.warning("broker %s: XCLAIM %s failed: %s",
                                   self.name, entry_id, exc)
                    continue
                for raw_id, fields in claimed or []:
                    # XCLAIM bumped the server-side counter by one
                    msg = self._to_message(
                        topic, as_str(raw_id), fields,
                        attempt=delivery_count + 1)
                    await self._deliver(stream, group, msg, handler)


@driver("pubsub.redis", "pubsub.redis-streams")
def _redis_pubsub(spec: ComponentSpec, metadata: dict[str, str]) -> PubSubBroker:
    """The backend follows the YAML, reference-style: a component file
    with ``redisHost`` (components/dapr-pubsub-redis.yaml:10-11) talks
    RESP to that server; without one, the durable sqlite broker stands
    in so local dev needs no Redis at all."""
    host = metadata.get("redisHost")
    if not host:
        from tasksrunner.pubsub.sqlite import _sqlite_pubsub
        return _sqlite_pubsub(spec, metadata)
    return RedisStreamsBroker(
        spec.name, host,
        max_attempts=int(metadata.get("maxRetries", 3)),
        redeliver_interval=float(metadata.get("redeliverIntervalSeconds", 0.5)),
        block_ms=int(metadata.get("blockMilliseconds", 200)),
        max_stream_len=int(metadata.get("maxLenApprox", 10_000)),
    )
