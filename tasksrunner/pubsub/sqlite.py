"""SQLite-backed durable pub/sub broker.

Fills the slot Azure Service Bus fills in the reference
(components/dapr-pubsub-svcbus.yaml, type ``pubsub.azure.servicebus``)
and Redis fills locally: a shared broker reachable by every app's
sidecar process through one database file. Delivery contract
(at-least-once, per-group fan-out, competing consumers via claim
leases, bounded redelivery then dead-letter) matches
tasksrunner/pubsub/base.py.

The visible backlog per group (`backlog()`) is the scale signal the
KEDA-style autoscaler watches — the analog of the `azure-servicebus`
scaler's messageCount on a topic subscription
(bicep/modules/container-apps/processor-backend-service.bicep:158-180).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import json
import logging
import pathlib
import sqlite3
import threading
import time
from typing import Any

from tasksrunner.component.registry import driver
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.ids import hex16
from tasksrunner.observability.metrics import metrics
from tasksrunner.pubsub.base import Handler, Message, Nack, PubSubBroker, Subscription

logger = logging.getLogger(__name__)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS groups (
    topic TEXT NOT NULL,
    grp   TEXT NOT NULL,
    PRIMARY KEY (topic, grp)
);
CREATE TABLE IF NOT EXISTS messages (
    id       TEXT PRIMARY KEY,
    topic    TEXT NOT NULL,
    data     TEXT NOT NULL,
    metadata TEXT NOT NULL,
    created  REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS deliveries (
    msg_id        TEXT NOT NULL,
    topic         TEXT NOT NULL,
    grp           TEXT NOT NULL,
    attempts      INTEGER NOT NULL DEFAULT 0,
    visible_at    REAL NOT NULL,
    claimed_until REAL NOT NULL DEFAULT 0,
    done          INTEGER NOT NULL DEFAULT 0,  -- 0 pending, 1 acked, 2 dead
    PRIMARY KEY (msg_id, grp)
);
CREATE INDEX IF NOT EXISTS idx_deliveries_pending
    ON deliveries (topic, grp, done, visible_at);
"""



def _locked(fn):
    """Serialise a db-touching method on the instance's _db_lock."""
    def wrapper(self, *args, **kwargs):
        with self._db_lock:
            return fn(self, *args, **kwargs)
    return wrapper


class SqliteBroker(PubSubBroker):
    def __init__(
        self,
        name: str,
        path: str | pathlib.Path,
        *,
        max_attempts: int = 3,
        retry_delay: float = 0.2,
        claim_lease: float = 30.0,
        poll_interval: float = 0.05,
        claim_batch: int = 64,
        gc_interval: float = 300.0,
        gc_retention: float = 3600.0,
    ):
        super().__init__(name)
        self.path = str(path)
        if self.path != ":memory:":
            pathlib.Path(self.path).parent.mkdir(parents=True, exist_ok=True)
        self.max_attempts = max_attempts
        self.retry_delay = retry_delay
        self.claim_lease = claim_lease
        self.poll_interval = poll_interval
        #: messages claimed per poll. Large batches amortise commits
        #: (throughput); small batches spread a backlog across
        #: competing consumers (fairness) — with slow handlers, one
        #: replica claiming 64 messages serialises 64×work while its
        #: peers idle (≙ Service Bus prefetch count)
        self.claim_batch = max(1, claim_batch)
        #: janitor cadence/age for dropping fully-settled messages; a
        #: long-running broker file must not grow without bound
        self.gc_interval = gc_interval
        self.gc_retention = gc_retention
        self._janitor: asyncio.Task | None = None
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        # WAL + NORMAL: fsync at checkpoint, not per-commit — the
        # standard durability/throughput point for local engines
        self._conn.execute("PRAGMA synchronous=NORMAL")
        # Writes on the hot path go through _write_txn, whose own retry
        # loop (sub-ms backoff) replaces sqlite's busy handler: the
        # built-in handler's first sleep is 1 ms and escalates to
        # 100 ms, which under publisher↔consumer convoys on the shared
        # file turned ~0.1 ms transactions into multi-ms publish p50s
        # (BASELINE.md round-4 attribution). _write_txn zeroes the
        # busy_timeout around its BEGIN IMMEDIATE; everything else
        # (schema init below, ad-hoc reads) keeps the 5 s cushion.
        self._conn.execute("PRAGMA busy_timeout=5000")
        # Decoupled checkpointing: never copy WAL→db inline on a
        # committing writer; a background thread with its own
        # connection runs PASSIVE checkpoints (see _checkpoint_loop).
        self._conn.execute("PRAGMA wal_autocheckpoint=0")
        self._conn.executescript(_SCHEMA)
        self._conn.commit()
        self._dirty = False          # set by _write_txn, cleared by checkpointer
        self._ckpt_stop = threading.Event()
        self._ckpt_thread: threading.Thread | None = None
        if self.path != ":memory:":
            self._ckpt_thread = threading.Thread(
                target=self._checkpoint_loop,
                name=f"broker-ckpt-{name}", daemon=True)
            self._ckpt_thread.start()
        self._tasks: list[asyncio.Task] = []
        self._closed = False
        # Async paths run db work on a dedicated thread so cross-process
        # lock waits (busy_timeout) never stall the event loop; _db_lock
        # additionally serialises the sync introspection methods
        # (backlog/dead_letters/gc) against it, keeping every
        # transaction on the shared connection atomic per thread.
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"broker-{name}")
        self._db_lock = threading.Lock()
        # Group-commit publish queue: concurrent publishers enqueue here
        # and one flush job on the db thread drains whatever accumulated
        # into a single transaction — commits amortise across the burst
        # (same reason the consumer side claims/acks in batches).
        self._pub_lock = threading.Lock()
        self._pub_pending: list[tuple] = []
        self._pub_flushing = False

    async def _run(self, fn, *args):
        return await asyncio.get_running_loop().run_in_executor(
            self._executor, fn, *args)

    # -- write-transaction plumbing --------------------------------------

    def _write_txn(self, body):
        """Run ``body(cursor)`` inside BEGIN IMMEDIATE…COMMIT, acquiring
        the cross-process write lock with a fast retry loop (0.2→2 ms
        exponential backoff, 5 s deadline) instead of sqlite's built-in
        busy handler (1→100 ms sleeps). Caller holds ``_db_lock``.
        """
        cur = self._conn.cursor()
        # fail-fast lock acquisition: sqlite's busy handler must not
        # add its 1→100 ms sleeps under our sub-ms backoff
        cur.execute("PRAGMA busy_timeout=0")
        delay = 0.0002
        deadline = time.monotonic() + 5.0
        try:
            while True:
                try:
                    cur.execute("BEGIN IMMEDIATE")
                    break
                except sqlite3.OperationalError as exc:
                    msg = str(exc).lower()
                    if "locked" not in msg and "busy" not in msg:
                        raise
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(delay)
                    delay = min(delay * 2, 0.002)
        finally:
            cur.execute("PRAGMA busy_timeout=5000")
        try:
            result = body(cur)
            self._conn.commit()
        except BaseException:
            self._conn.rollback()
            raise
        self._dirty = True
        return result

    def _checkpoint_loop(self) -> None:
        """Background PASSIVE WAL checkpointing on a dedicated
        connection (runs concurrently with the main connection — WAL
        readers/writers are never blocked by PASSIVE mode). Keeps the
        checkpoint's page-copy IO off the commit path entirely: with
        ``wal_autocheckpoint=0`` no commit ever pays it inline."""
        conn = None
        while not self._ckpt_stop.wait(0.25):
            if not self._dirty:
                continue
            self._dirty = False
            try:
                if conn is None:
                    conn = sqlite3.connect(self.path, timeout=1.0)
                conn.execute("PRAGMA wal_checkpoint(PASSIVE)")
            except sqlite3.Error:  # pragma: no cover - transient; retry next tick
                self._dirty = True
        if conn is not None:
            try:
                conn.execute("PRAGMA wal_checkpoint(PASSIVE)")
                conn.close()
            except sqlite3.Error:  # pragma: no cover
                pass

    # -- publish ---------------------------------------------------------

    async def publish(self, topic: str, data: Any, *, metadata=None) -> str:
        msg_id = hex16()
        # serialize on the caller so a bad payload fails its own publish,
        # never the shared flush batch
        doc = json.dumps(data)
        meta = json.dumps(dict(metadata or {}))
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        row = (msg_id, topic, doc, meta, loop, fut)
        with self._pub_lock:
            self._pub_pending.append(row)
            if not self._pub_flushing:
                try:
                    self._executor.submit(self._flush_publishes)
                except RuntimeError:
                    # executor shut down (publish after aclose): fail this
                    # publish cleanly and leave the flag consistent
                    self._pub_pending.remove(row)
                    raise
                self._pub_flushing = True
        await fut
        return msg_id

    def _flush_publishes(self) -> None:
        """Flush one accumulated publish batch in a single transaction
        (db thread). Re-submits itself if more arrived meanwhile, so
        consumer-side jobs (claim/ack) interleave FIFO on the shared
        single-thread executor instead of starving behind a drain loop."""
        with self._pub_lock:
            batch = self._pub_pending
            if not batch:
                self._pub_flushing = False
                return
            self._pub_pending = []
        # depth the publish queue reached before this flush drained it;
        # sampled once per batch on the db thread, off the event loop
        metrics.set_gauge("broker_publish_queue_depth", len(batch),
                          pubsub=self.name)
        try:
            with self._db_lock:
                self._publish_rows([b[:4] for b in batch])
        except BaseException:
            # batch failed: retry each message alone so one poisoned
            # row cannot fail its neighbours; report per-message
            for row in batch:
                try:
                    with self._db_lock:
                        self._publish_rows([row[:4]])
                except BaseException as single_exc:
                    self._resolve(row, single_exc)
                else:
                    self._resolve(row, None)
        else:
            for row in batch:
                self._resolve(row, None)
        with self._pub_lock:
            if self._pub_pending:
                try:
                    self._executor.submit(self._flush_publishes)
                except RuntimeError:  # shutdown race: fail the stragglers
                    self._pub_flushing = False
                    for row in self._pub_pending:
                        self._resolve(row, RuntimeError("broker closed"))
                    self._pub_pending = []
            else:
                self._pub_flushing = False

    @staticmethod
    def _resolve(row: tuple, exc: BaseException | None) -> None:
        _, _, _, _, loop, fut = row
        def _set() -> None:
            if fut.done():
                return
            if exc is None:
                fut.set_result(None)
            else:
                fut.set_exception(exc)
        try:
            loop.call_soon_threadsafe(_set)
        except RuntimeError:  # caller's loop already closed (shutdown)
            pass

    def _publish_rows(self, rows: list[tuple]) -> None:
        """One transaction inserting N messages + their delivery fan-out.
        Caller holds _db_lock."""
        now = time.time()

        def body(cur: sqlite3.Cursor) -> None:
            cur.executemany(
                "INSERT INTO messages(id, topic, data, metadata, created) VALUES (?,?,?,?,?)",
                [(msg_id, topic, doc, meta, now) for msg_id, topic, doc, meta in rows],
            )
            groups_by_topic: dict[str, list[str]] = {}
            deliveries = []
            for msg_id, topic, _, _ in rows:
                if topic not in groups_by_topic:
                    groups_by_topic[topic] = [r[0] for r in cur.execute(
                        "SELECT grp FROM groups WHERE topic = ?", (topic,)
                    ).fetchall()]
                for grp in groups_by_topic[topic]:
                    deliveries.append((msg_id, topic, grp, now))
            if deliveries:
                cur.executemany(
                    "INSERT INTO deliveries(msg_id, topic, grp, visible_at) VALUES (?,?,?,?)",
                    deliveries,
                )

        self._write_txn(body)

    async def ensure_group(self, topic: str, group: str) -> None:
        await self._run(self._ensure_group_sync, topic, group)

    @_locked
    def _ensure_group_sync(self, topic: str, group: str) -> None:
        self._write_txn(lambda cur: cur.execute(
            "INSERT OR IGNORE INTO groups(topic, grp) VALUES (?, ?)",
            (topic, group)))

    # -- consume ---------------------------------------------------------

    @_locked
    def _claim_and_ack(self, topic: str, group: str, limit: int,
                       ack_ids: list[str]) -> list[Message]:
        """One transaction settling the previous batch's acks AND
        claiming the next batch — the consumer's steady-state write
        traffic on the shared file is one commit per batch, not two.
        (Acks ride the next claim; the poll loop flushes stragglers
        with _ack_many when it goes idle or is cancelled.)"""
        now = time.time()
        cur = self._conn.cursor()
        # read-only emptiness probe first (WAL snapshot, no lock): an
        # idle consumer polls every few ms, and BEGIN IMMEDIATE on every
        # empty poll would hold the db's single write lock against the
        # publisher in the other process — measured as milliseconds of
        # publish latency at concurrency. Competing consumers may both
        # pass the probe; the re-SELECT inside the write transaction
        # below keeps claims exclusive.
        probe = cur.execute(
            "SELECT 1 FROM deliveries WHERE topic = ? AND grp = ? "
            "AND done = 0 AND visible_at <= ? AND claimed_until <= ? LIMIT 1",
            (topic, group, now, now),
        ).fetchone()
        if probe is None and not ack_ids:
            return []

        def body(cur: sqlite3.Cursor) -> list:
            if ack_ids:
                cur.executemany(
                    "UPDATE deliveries SET done = 1 WHERE msg_id = ? AND grp = ?",
                    [(m, group) for m in ack_ids],
                )
            if probe is None:
                return []
            rows = cur.execute(
                "SELECT d.msg_id, d.attempts, m.data, m.metadata FROM deliveries d "
                "JOIN messages m ON m.id = d.msg_id "
                "WHERE d.topic = ? AND d.grp = ? AND d.done = 0 "
                "AND d.visible_at <= ? AND d.claimed_until <= ? "
                "ORDER BY d.visible_at LIMIT ?",
                (topic, group, now, now, limit),
            ).fetchall()
            if rows:
                cur.executemany(
                    "UPDATE deliveries SET claimed_until = ?, attempts = attempts + 1 "
                    "WHERE msg_id = ? AND grp = ?",
                    [(now + self.claim_lease, r[0], group) for r in rows],
                )
            return rows

        rows = self._write_txn(body)
        return [
            Message(id=msg_id, topic=topic, data=json.loads(data),
                    metadata=json.loads(metadata), attempt=attempts + 1)
            for msg_id, attempts, data, metadata in rows
        ]

    def _claim_batch(self, topic: str, group: str, limit: int) -> list[Message]:
        return self._claim_and_ack(topic, group, limit, [])

    def _claim_one(self, topic: str, group: str) -> Message | None:
        batch = self._claim_batch(topic, group, 1)
        return batch[0] if batch else None

    @_locked
    def _ack(self, msg_id: str, group: str) -> None:
        self._write_txn(lambda cur: cur.execute(
            "UPDATE deliveries SET done = 1 WHERE msg_id = ? AND grp = ?",
            (msg_id, group)))

    @_locked
    def _ack_many(self, msg_ids: list[str], group: str) -> None:
        self._write_txn(lambda cur: cur.executemany(
            "UPDATE deliveries SET done = 1 WHERE msg_id = ? AND grp = ?",
            [(m, group) for m in msg_ids]))

    @_locked
    def _extend_leases(self, msg_ids: list[str], group: str) -> float:
        """Re-lease still-unprocessed claims (slow handlers must not let
        the batch tail expire into duplicate delivery)."""
        until = time.time() + self.claim_lease
        self._write_txn(lambda cur: cur.executemany(
            "UPDATE deliveries SET claimed_until = ? WHERE msg_id = ? AND grp = ?",
            [(until, m, group) for m in msg_ids]))
        return until

    def _dlq_gauge(self, topic: str, group: str) -> None:
        """Refresh broker_dlq_depth for one topic/group (db thread,
        caller holds _db_lock)."""
        row = self._conn.execute(
            "SELECT COUNT(*) FROM deliveries WHERE topic = ? AND grp = ? "
            "AND done = 2", (topic, group)).fetchone()
        metrics.set_gauge("broker_dlq_depth", float(row[0]),
                          topic=topic, group=group)

    @_locked
    def _nack(self, msg: Message, group: str, hint: Nack | None = None) -> None:
        counts = hint is None or hint.counts_attempt
        delay = (self.retry_delay if hint is None or hint.retry_after is None
                 else hint.retry_after)
        if counts and msg.attempt >= self.max_attempts:
            logger.warning(
                "dead-lettering message %s on %s/%s after %d attempts",
                msg.id, msg.topic, group, msg.attempt,
            )
            self._write_txn(lambda cur: cur.execute(
                "UPDATE deliveries SET done = 2 WHERE msg_id = ? AND grp = ?",
                (msg.id, group)))
            self._dlq_gauge(msg.topic, group)
        else:
            # claiming charged this attempt up front; a not-ready nack
            # (counts_attempt=False — the consumer never processed the
            # message) refunds it so warmup backoff can't dead-letter
            refund = "" if counts else ", attempts = attempts - 1"
            self._write_txn(lambda cur: cur.execute(
                "UPDATE deliveries SET visible_at = ?, claimed_until = 0"
                f"{refund} WHERE msg_id = ? AND grp = ?",
                (time.time() + delay, msg.id, group)))

    async def subscribe(self, topic: str, group: str, handler: Handler) -> Subscription:
        await self.ensure_group(topic, group)
        if self._janitor is None and self.gc_interval > 0:
            # one janitor per broker instance, started with the first
            # consumer (producers-only processes never mutate history)
            self._janitor = asyncio.create_task(self._janitor_loop())
            self._tasks.append(self._janitor)
        stop = asyncio.Event()

        async def poll_loop() -> None:
            # acks accumulated from the previous batch; settled inside
            # the next claim's transaction (_claim_and_ack) so steady-
            # state consumption costs one write commit per batch
            acks: list[str] = []
            try:
                while not stop.is_set() and not self._closed:
                    batch = await self._run(self._claim_and_ack, topic,
                                            group, self.claim_batch, acks)
                    acks = []
                    if not batch:
                        try:
                            await asyncio.wait_for(stop.wait(), timeout=self.poll_interval)
                        except asyncio.TimeoutError:
                            pass
                        continue
                    lease_deadline = time.time() + self.claim_lease
                    for i, msg in enumerate(batch):
                        # slow handlers: re-lease the unprocessed tail
                        # before it expires into duplicate delivery
                        if time.time() > lease_deadline - self.claim_lease / 2:
                            rest = [m.id for m in batch[i:]]
                            lease_deadline = await self._run(
                                self._extend_leases, rest, group)
                        try:
                            ok = await handler(msg)
                        except asyncio.CancelledError:
                            raise
                        except Exception:
                            logger.exception("handler error on topic %s group %s",
                                             topic, group)
                            ok = False
                        if ok:
                            acks.append(msg.id)
                        else:
                            await self._run(self._nack, msg, group,
                                            ok if isinstance(ok, Nack) else None)
            finally:
                # cancelled (or loop exit) with unsettled acks: flush
                # them now — shutdown must not cause redelivery of
                # successfully processed messages; direct sync call —
                # the executor may already be rejecting work
                if acks:
                    self._ack_many(acks, group)

        task = asyncio.create_task(poll_loop())
        self._tasks.append(task)

        async def cancel() -> None:
            stop.set()
            try:
                await task
            except asyncio.CancelledError:
                # broker.aclose() may have force-cancelled the poll loop
                # already (shared broker, multiple runtimes) — reap it;
                # but if *we* were cancelled while waiting, propagate
                if not task.cancelled():
                    raise

        return Subscription(topic=topic, group=group, _cancel=cancel)

    async def _janitor_loop(self) -> None:
        """Periodically drop messages settled in every group (≙ broker
        retention: Service Bus removes completed messages; this file
        would otherwise grow forever)."""
        while not self._closed:
            await asyncio.sleep(self.gc_interval)
            if self._closed:
                return
            try:
                dropped = await self._run(
                    lambda: self.gc(older_than=self.gc_retention))
            except Exception:  # pragma: no cover - defensive
                logger.exception("broker %s gc failed", self.name)
                continue
            if dropped:
                logger.info("broker %s gc dropped %d settled message(s)",
                            self.name, dropped)

    # -- introspection ---------------------------------------------------

    @_locked
    def backlog(self, topic: str, group: str) -> int:
        """Visible, un-acked message count — the autoscale signal."""
        (n,) = self._conn.execute(
            "SELECT COUNT(*) FROM deliveries WHERE topic = ? AND grp = ? AND done = 0",
            (topic, group),
        ).fetchone()
        return n

    @_locked
    def dead_letters(self, topic: str, group: str) -> list[str]:
        rows = self._conn.execute(
            "SELECT msg_id FROM deliveries WHERE topic = ? AND grp = ? AND done = 2",
            (topic, group),
        ).fetchall()
        return [r[0] for r in rows]

    @_locked
    def dead_letter_detail(self, topic: str, group: str) -> list[dict]:
        """Full dead-letter records, for operator inspection (≙ peeking
        a Service Bus subscription's dead-letter queue)."""
        rows = self._conn.execute(
            "SELECT d.msg_id, d.attempts, m.data, m.metadata, m.created "
            "FROM deliveries d JOIN messages m ON m.id = d.msg_id "
            "WHERE d.topic = ? AND d.grp = ? AND d.done = 2 "
            "ORDER BY m.created",
            (topic, group),
        ).fetchall()
        return [
            {"id": msg_id, "attempts": attempts, "data": json.loads(data),
             "metadata": json.loads(metadata), "created": created}
            for msg_id, attempts, data, metadata, created in rows
        ]

    @_locked
    def requeue_dead_letters(self, topic: str, group: str,
                             msg_ids: list[str] | None = None) -> int:
        """Return dead-letters to the pending queue with a fresh
        attempt budget (≙ Service Bus dead-letter resubmission)."""
        now = time.time()
        sql = ("UPDATE deliveries SET done = 0, attempts = 0, "
               "visible_at = ?, claimed_until = 0 "
               "WHERE topic = ? AND grp = ? AND done = 2")
        params: list = [now, topic, group]
        if msg_ids is not None:
            if not msg_ids:
                return 0
            sql += f" AND msg_id IN ({', '.join('?' for _ in msg_ids)})"
            params.extend(msg_ids)
        requeued = self._write_txn(lambda cur: cur.execute(sql, params)).rowcount
        self._dlq_gauge(topic, group)
        return requeued

    @_locked
    def gc(self, *, older_than: float = 3600.0) -> int:
        """Drop messages fully settled in every group. Pending (done=0)
        AND dead-lettered (done=2) deliveries pin their message: the
        DLQ retains payloads until an operator requeues or purges them
        (Service Bus keeps DLQ messages until explicitly handled)."""
        cutoff = time.time() - older_than

        def body(cur: sqlite3.Cursor) -> int:
            cur.execute(
                "DELETE FROM messages WHERE created < ? AND NOT EXISTS "
                "(SELECT 1 FROM deliveries d WHERE d.msg_id = messages.id "
                "AND d.done IN (0, 2))",
                (cutoff,),
            )
            dropped = cur.rowcount
            cur.execute(
                "DELETE FROM deliveries WHERE done != 0 AND NOT EXISTS "
                "(SELECT 1 FROM messages m WHERE m.id = deliveries.msg_id)"
            )
            return dropped

        return self._write_txn(body)

    @_locked
    def purge_dead_letters(self, topic: str, group: str,
                           msg_ids: list[str] | None = None) -> int:
        """Explicitly discard dead letters (the operator's 'handled by
        deletion' path); their message rows become gc-able."""
        sql = ("DELETE FROM deliveries WHERE topic = ? AND grp = ? AND done = 2")
        params: list = [topic, group]
        if msg_ids is not None:
            if not msg_ids:
                return 0
            sql += f" AND msg_id IN ({', '.join('?' for _ in msg_ids)})"
            params.extend(msg_ids)
        purged = self._write_txn(lambda cur: cur.execute(sql, params)).rowcount
        self._dlq_gauge(topic, group)
        return purged

    def close_sync(self) -> None:
        """Synchronous close for out-of-band (no event loop) users —
        inspection CLIs and the autoscaler's backlog reader."""
        self._closed = True
        self._ckpt_stop.set()
        self._executor.shutdown(wait=False)
        self._conn.close()

    async def aclose(self) -> None:
        self._closed = True
        self._ckpt_stop.set()
        for task in self._tasks:
            task.cancel()
        for task in self._tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        # don't block the loop on a possibly busy-waiting db thread
        await asyncio.get_running_loop().run_in_executor(
            None, lambda: self._executor.shutdown(wait=True))
        if self._ckpt_thread is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, self._ckpt_thread.join)
        self._conn.close()


def default_broker_path(name: str) -> str:
    """The brokerPath a component gets when its YAML names none —
    shared by the driver, the autoscaler's out-of-band reader, and the
    dlq CLI so they can never desynchronize."""
    return ".tasksrunner/pubsub-" + name + ".db"


def open_for_inspection(spec: ComponentSpec,
                        base_dir: pathlib.Path | str | None = None,
                        *, must_exist: bool = True) -> SqliteBroker:
    """Open a component's shared broker file out-of-band (the position
    KEDA occupies: read the broker, not the app). Relative brokerPath
    resolves against ``base_dir`` — the run-config's directory, which
    is what the serving apps resolve against. Close with
    :meth:`SqliteBroker.close_sync`.

    Raises ComponentError for components whose broker is NOT the
    shared sqlite file (a ``pubsub.redis`` with a live ``redisHost``
    keeps its dead letters in Redis streams — inspecting the sqlite
    fallback file would silently answer from the wrong store).
    """
    from tasksrunner.errors import ComponentError

    if not spec.type.startswith("pubsub."):
        raise ComponentError(f"component {spec.name!r} is {spec.type}, not a pubsub")
    # mirror the redis driver's decision (pubsub/redis.py: empty host →
    # sqlite fallback): a non-empty string, or a secretRef (resolves to
    # a real host), means the live broker is Redis streams
    host = spec.metadata.get("redisHost")
    if host is not None and (not isinstance(host, str) or host.strip()):
        raise ComponentError(
            f"component {spec.name!r} is served by the Redis streams broker "
            f"(redisHost set); its dead letters live on the "
            f"'<topic>:dead' streams in Redis, not in a local broker file")
    broker_path = spec.metadata.get("brokerPath")
    if not isinstance(broker_path, str):
        broker_path = default_broker_path(spec.name)
    path = pathlib.Path(broker_path)
    if not path.is_absolute():
        path = pathlib.Path(base_dir or pathlib.Path.cwd()) / path
    if must_exist and not path.is_file():
        raise ComponentError(
            f"broker file {path} does not exist — has anything published "
            "through this component yet? (relative brokerPath resolves "
            "against the run-config's directory; pass --base-dir)")
    return SqliteBroker(spec.name, path)


@driver("pubsub.sqlite", "pubsub.azure.servicebus")
def _sqlite_pubsub(spec: ComponentSpec, metadata: dict[str, str]) -> SqliteBroker:
    """Durable local broker; cloud-typed component files (the
    reference's dapr-pubsub-svcbus.yaml shape) run unchanged against
    it. `brokerPath` picks the shared db file. ``pubsub.redis`` files
    land here too when they carry no redisHost (see pubsub/redis.py)."""
    return SqliteBroker(
        spec.name,
        metadata.get("brokerPath", default_broker_path(spec.name)),
        max_attempts=int(metadata.get("maxRetries", 3)),
        retry_delay=float(metadata.get("retryDelaySeconds", 0.2)),
        poll_interval=float(metadata.get("pollIntervalSeconds", 0.05)),
        # how long a claimed-but-unacked message stays invisible before
        # a crashed consumer's claim expires into redelivery (≙ Service
        # Bus lock duration)
        claim_lease=float(metadata.get("claimLeaseSeconds", 30.0)),
        # prefetch: messages claimed per poll (throughput ↔ competing-
        # consumer fairness; ≙ Service Bus maxConcurrentHandlers/prefetch)
        claim_batch=int(metadata.get("claimBatchSize", 64)),
        # settled-message retention (0 disables the janitor)
        gc_interval=float(metadata.get("gcIntervalSeconds", 300.0)),
        gc_retention=float(metadata.get("gcRetentionSeconds", 3600.0)),
    )
