"""In-process pub/sub broker.

The zero-dependency test double for the broker slot (reference local
slot: Redis via components/dapr-pubsub-redis.yaml). Honors the full
delivery contract — per-group fan-out, round-robin competing consumers,
nack → redelivery with bounded retries — but only within one process
and without durability across restarts (use SqliteBroker for that).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from collections import defaultdict
from typing import Any

from tasksrunner.component.registry import driver
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.ids import hex16
from tasksrunner.pubsub.base import Handler, Message, Nack, PubSubBroker, Subscription

logger = logging.getLogger(__name__)


class _Group:
    """One consumer group on one topic: a queue + competing consumers."""

    def __init__(self) -> None:
        self.queue: asyncio.Queue[Message] = asyncio.Queue()
        self.consumers: list[Handler] = []
        self.rr = itertools.count()
        self.pump: asyncio.Task | None = None


class InMemoryBroker(PubSubBroker):
    def __init__(self, name: str = "memory", *, max_attempts: int = 3,
                 retry_delay: float = 0.05):
        super().__init__(name)
        self.max_attempts = max_attempts
        self.retry_delay = retry_delay
        self._groups: dict[str, dict[str, _Group]] = defaultdict(dict)
        #: messages that exhausted retries (inspectable dead-letter list)
        self.dead_letters: list[Message] = []
        self._closed = False

    async def publish(self, topic: str, data: Any, *, metadata=None) -> str:
        msg_id = hex16()
        for group in self._groups.get(topic, {}).values():
            group.queue.put_nowait(
                Message(id=msg_id, topic=topic, data=data, metadata=dict(metadata or {}))
            )
        return msg_id

    async def ensure_group(self, topic: str, group: str) -> None:
        if group not in self._groups[topic]:
            self._groups[topic][group] = _Group()

    async def subscribe(self, topic: str, group: str, handler: Handler) -> Subscription:
        await self.ensure_group(topic, group)
        g = self._groups[topic][group]
        g.consumers.append(handler)
        if g.pump is None:
            g.pump = asyncio.create_task(self._pump(topic, group, g))

        async def cancel() -> None:
            if handler in g.consumers:
                g.consumers.remove(handler)
            if not g.consumers and g.pump is not None:
                g.pump.cancel()
                g.pump = None

        return Subscription(topic=topic, group=group, _cancel=cancel)

    async def _pump(self, topic: str, group_name: str, g: _Group) -> None:
        while not self._closed:
            msg = await g.queue.get()
            if not g.consumers:
                # group exists but no live consumer: park it back and wait
                await asyncio.sleep(self.retry_delay)
                g.queue.put_nowait(msg)
                continue
            handler = g.consumers[next(g.rr) % len(g.consumers)]
            try:
                ok = await handler(msg)
            except Exception:
                logger.exception("handler error on topic %s group %s", topic, group_name)
                ok = False
            if not ok:
                hint = ok if isinstance(ok, Nack) else None
                counts = hint is None or hint.counts_attempt
                delay = (self.retry_delay if hint is None
                         or hint.retry_after is None else hint.retry_after)
                if counts and msg.attempt >= self.max_attempts:
                    logger.warning(
                        "dead-lettering message %s on %s/%s after %d attempts",
                        msg.id, topic, group_name, msg.attempt,
                    )
                    self.dead_letters.append(msg)
                else:
                    # a counts_attempt=False nack (consumer not ready,
                    # never processed the message) parks it without
                    # burning an attempt — warmup can't dead-letter
                    if counts:
                        msg.attempt += 1
                    asyncio.get_running_loop().call_later(
                        delay, g.queue.put_nowait, msg
                    )

    async def aclose(self) -> None:
        self._closed = True
        for groups in self._groups.values():
            for g in groups.values():
                if g.pump is not None:
                    g.pump.cancel()
                    g.pump = None


@driver("pubsub.in-memory", "pubsub.memory")
def _memory_pubsub(spec: ComponentSpec, metadata: dict[str, str]) -> InMemoryBroker:
    return InMemoryBroker(
        spec.name,
        max_attempts=int(metadata.get("maxRetries", 3)),
        retry_delay=float(metadata.get("retryDelaySeconds", 0.05)),
    )
