"""AppClient — the SDK services program against (≙ DaprClient).

Method-for-method parity with the reference's client usage:

* ``invoke_method`` — Pages/Tasks/Index.cshtml.cs:48, Create :46, Edit :38/:66;
* ``save_state`` / ``get_state`` / ``delete_state`` — TasksStoreManager.cs:35/:73/:49;
* ``query_state`` — TasksStoreManager.cs:56-61, :125-130;
* ``publish_event`` — TasksStoreManager.cs:151-156;
* ``invoke_binding`` — ExternalTasksProcessorController.cs:38-43,
  docs module 6 TasksNotifierController.cs:56;
* ``get_secret`` — Dapr secret API (SURVEY.md §5.6).

Two transports behind one surface: ``AppClient.direct(runtime)`` binds
straight to an in-process Runtime (tests, single-process mode);
``AppClient.http(port)`` talks to a sidecar over localhost HTTP, which
is how real services run. Both must behave identically — the
integration suite runs the same scenarios through each.
"""

from __future__ import annotations

import abc
import json
import os
from typing import Any

from tasksrunner.bindings.base import BindingResponse
from tasksrunner.errors import (
    ActorFencedError,
    EtagMismatch,
    InvocationError,
    InvocationStatusError,
    PlacementEpochError,
    QueryError,
    SaturatedError,
    SecretNotFound,
    TasksRunnerError,
)
from tasksrunner.runtime import Runtime
from tasksrunner.security import TOKEN_ENV, TOKEN_HEADER
from tasksrunner.state.base import StateItem
from tasksrunner.state.placement import PLACEMENT_EPOCH_HEADER

DEFAULT_SIDECAR_PORT = 3500
PORT_ENV = "TASKSRUNNER_HTTP_PORT"


def _retry_after_seconds(headers: dict[str, str] | None) -> float | None:
    """Seconds from a Retry-After header, if present and numeric.

    A shedding replica (429) or an open breaker / protected target
    (503) tells clients how long to stay away; the resiliency retry
    loop stretches its next delay to honor it. The HTTP-date form is
    ignored — the runtime only ever emits delta-seconds."""
    if not headers:
        return None
    raw = next((v for k, v in headers.items()
                if k.lower() == "retry-after"), None)
    if raw is None:
        return None
    try:
        return max(0.0, float(raw))
    except (TypeError, ValueError):
        return None


def _attach_retry_after(exc: Exception, status: int,
                        headers: dict[str, str] | None) -> None:
    if status in (429, 503):
        hint = _retry_after_seconds(headers)
        if hint is not None:
            exc.retry_after = hint


class InvocationResponse:
    def __init__(self, status: int, headers: dict[str, str], body: bytes):
        self.status = status
        self.headers = headers
        self.body = body

    @property
    def ok(self) -> bool:
        return 200 <= self.status < 300

    def json(self) -> Any:
        if not self.body:
            return None
        return json.loads(self.body)

    def raise_for_status(self) -> "InvocationResponse":
        if not self.ok:
            detail = self.body[:300].decode("utf-8", "replace")
            exc = InvocationStatusError(
                f"invocation returned {self.status}: {detail}",
                status=self.status)
            _attach_retry_after(exc, self.status, self.headers)
            raise exc
        return self


class _Transport(abc.ABC):
    @abc.abstractmethod
    async def save_state(self, store, items): ...
    @abc.abstractmethod
    async def get_state(self, store, key) -> StateItem | None: ...
    @abc.abstractmethod
    async def delete_state(self, store, key, etag): ...
    @abc.abstractmethod
    async def bulk_get_state(self, store, keys) -> list[dict]: ...
    @abc.abstractmethod
    async def query_state(self, store, query) -> dict: ...
    @abc.abstractmethod
    async def transact_state(self, store, operations): ...
    @abc.abstractmethod
    async def publish(self, pubsub, topic, data, raw): ...
    @abc.abstractmethod
    async def invoke_binding(self, name, operation, data, metadata) -> BindingResponse: ...
    @abc.abstractmethod
    async def invoke(self, app_id, method_path, http_method, query, headers, body): ...
    @abc.abstractmethod
    async def get_secret(self, store, key) -> dict[str, str]: ...
    @abc.abstractmethod
    async def bulk_secrets(self, store) -> dict[str, str]: ...
    @abc.abstractmethod
    async def invoke_actor(self, actor_type, actor_id, method, data) -> Any: ...
    @abc.abstractmethod
    async def register_actor_reminder(self, actor_type, actor_id, name,
                                      due_seconds, period_seconds, data): ...
    @abc.abstractmethod
    async def unregister_actor_reminder(self, actor_type, actor_id, name): ...
    @abc.abstractmethod
    async def get_actor_state(self, actor_type, actor_id) -> dict: ...
    async def close(self): ...


class _DirectTransport(_Transport):
    def __init__(self, runtime: Runtime):
        self.runtime = runtime

    async def save_state(self, store, items):
        await self.runtime.save_state(store, items)

    async def get_state(self, store, key):
        return await self.runtime.get_state(store, key)

    async def delete_state(self, store, key, etag):
        await self.runtime.delete_state(store, key, etag=etag)

    async def bulk_get_state(self, store, keys):
        return await self.runtime.bulk_get_state(store, keys)

    async def query_state(self, store, query):
        return await self.runtime.query_state(store, query)

    async def transact_state(self, store, operations):
        await self.runtime.transact_state(store, operations)

    async def publish(self, pubsub, topic, data, raw):
        await self.runtime.publish(pubsub, topic, data, raw=raw)

    async def invoke_binding(self, name, operation, data, metadata):
        return await self.runtime.invoke_output_binding(name, operation, data, metadata)

    async def invoke(self, app_id, method_path, http_method, query, headers, body):
        return await self.runtime.invoke(
            app_id, method_path, http_method=http_method, query=query,
            headers=headers, body=body)

    async def get_secret(self, store, key):
        return self.runtime.get_secret(store, key)

    async def bulk_secrets(self, store):
        return self.runtime.bulk_secrets(store)

    async def invoke_actor(self, actor_type, actor_id, method, data):
        return await self.runtime.invoke_actor(actor_type, actor_id,
                                               method, data)

    async def register_actor_reminder(self, actor_type, actor_id, name,
                                      due_seconds, period_seconds, data):
        await self.runtime.register_actor_reminder(
            actor_type, actor_id, name, due_seconds=due_seconds,
            period_seconds=period_seconds, data=data)

    async def unregister_actor_reminder(self, actor_type, actor_id, name):
        await self.runtime.unregister_actor_reminder(actor_type, actor_id, name)

    async def get_actor_state(self, actor_type, actor_id):
        return await self.runtime.get_actor_state(actor_type, actor_id)


class _HTTPTransport(_Transport):
    """Talks to the local sidecar's /v1.0 API, mapping HTTP errors back
    to the same exception types the direct transport raises."""

    def __init__(self, base_url: str):
        self.base = base_url.rstrip("/")
        self._session = None
        # elastic placement: last routing epoch learned per store. A
        # flip makes the next stamped request 409 with the new epoch in
        # the reply header; _state_request refreshes and retries once,
        # so a live migration costs callers one extra round trip, never
        # a failed operation.
        self._placement_epochs: dict[str, int] = {}

    async def _request(self, method: str, path: str, *, json_body=None,
                       headers=None, data=None, params=None):
        if self._session is None:
            import aiohttp
            self._session = aiohttp.ClientSession()
        url = self.base + path
        # carry the caller's trace context over the app→sidecar hop —
        # without this, every sidecar operation starts a fresh trace and
        # transactions fragment (the direct transport shares the context
        # in-process; both transports must behave identically)
        from tasksrunner.observability.tracing import (
            TRACEPARENT_HEADER,
            outgoing_headers,
        )
        headers = dict(headers or {})
        if TRACEPARENT_HEADER not in headers:
            headers.update(outgoing_headers())
        token = os.environ.get(TOKEN_ENV)
        if token:
            headers.setdefault(TOKEN_HEADER, token)
        try:
            async with self._session.request(
                method, url, json=json_body, data=data,
                headers=headers, params=params) as resp:
                # lowercase header names: aiohttp preserves wire casing
                # ("Etag"), and lookups below are lowercase
                response_headers = {k.lower(): v for k, v in resp.headers.items()}
                return resp.status, response_headers, await resp.read()
        except OSError as exc:
            raise InvocationError(f"sidecar unreachable at {url}: {exc}") from exc

    async def _state_request(self, method: str, path: str, store: str,
                             *, json_body=None, headers=None):
        """State-path request with the placement-epoch handshake: stamp
        the cached epoch, and on a 409 that carries the live epoch in
        its reply header, refresh the cache and retry exactly once."""
        headers = dict(headers or {})
        known = self._placement_epochs.get(store)
        if known is not None:
            headers[PLACEMENT_EPOCH_HEADER] = str(known)
        status, resp_headers, body = await self._request(
            method, path, json_body=json_body, headers=headers)
        fresh = resp_headers.get(PLACEMENT_EPOCH_HEADER)
        if status == 409 and fresh is not None:
            self._placement_epochs[store] = int(fresh)
            headers[PLACEMENT_EPOCH_HEADER] = fresh
            status, resp_headers, body = await self._request(
                method, path, json_body=json_body, headers=headers)
            fresh = resp_headers.get(PLACEMENT_EPOCH_HEADER)
            if status == 409 and fresh is not None:
                # flipped again mid-retry — surface the typed error so
                # resiliency policies can decide, cache the newest epoch
                self._placement_epochs[store] = int(fresh)
                raise PlacementEpochError(
                    f"store {store!r} placement epoch advanced twice "
                    f"during one call", current_epoch=int(fresh))
        return status, resp_headers, body

    @staticmethod
    def _raise(status: int, body: bytes, *, context: str,
               headers: dict[str, str] | None = None) -> None:
        try:
            message = json.loads(body).get("error", "")
        except (ValueError, AttributeError):
            message = body[:200].decode("utf-8", "replace")
        exc_type: type[TasksRunnerError]
        if status == 409 and headers and PLACEMENT_EPOCH_HEADER in headers:
            raise PlacementEpochError(
                f"{context}: {message or status}",
                current_epoch=int(headers[PLACEMENT_EPOCH_HEADER]))
        if status == 409 and "actor" in context:
            exc_type = ActorFencedError
        elif status == 409:
            exc_type = EtagMismatch
        elif status == 429:
            exc_type = SaturatedError
        elif status == 404 and "secret" in context:
            exc_type = SecretNotFound
        elif status == 400 and "query" in context:
            exc_type = QueryError
        else:
            exc_type = TasksRunnerError
        exc = exc_type(f"{context}: {message or status}")
        exc.http_status = status
        _attach_retry_after(exc, status, headers)
        raise exc

    async def save_state(self, store, items):
        status, headers, body = await self._state_request(
            "POST", f"/v1.0/state/{store}", store, json_body=items)
        if status >= 300:
            self._raise(status, body, context=f"save state {store}", headers=headers)

    async def get_state(self, store, key):
        status, headers, body = await self._state_request(
            "GET", f"/v1.0/state/{store}/{key}", store)
        if status == 204 or (status == 200 and not body):
            return None
        if status >= 300:
            self._raise(status, body, context=f"get state {store}", headers=headers)
        return StateItem(key=key, value=json.loads(body),
                         etag=headers.get("etag", ""))

    async def delete_state(self, store, key, etag):
        req_headers = {"if-match": etag} if etag else {}
        status, headers, body = await self._state_request(
            "DELETE", f"/v1.0/state/{store}/{key}", store, headers=req_headers)
        if status >= 300:
            self._raise(status, body, context=f"delete state {store}", headers=headers)

    async def bulk_get_state(self, store, keys):
        status, headers, body = await self._state_request(
            "POST", f"/v1.0/state/{store}/bulk", store,
            json_body={"keys": keys})
        if status >= 300:
            self._raise(status, body, context=f"bulk get state {store}", headers=headers)
        return json.loads(body)

    async def query_state(self, store, query):
        status, headers, body = await self._state_request(
            "POST", f"/v1.0/state/{store}/query", store, json_body=query)
        if status >= 300:
            self._raise(status, body, context=f"query state {store}", headers=headers)
        return json.loads(body)

    async def transact_state(self, store, operations):
        status, headers, body = await self._state_request(
            "POST", f"/v1.0/state/{store}/transaction", store,
            json_body={"operations": operations})
        if status >= 300:
            self._raise(status, body, context=f"state transaction {store}", headers=headers)

    async def publish(self, pubsub, topic, data, raw):
        params = {"metadata.rawPayload": "true"} if raw else None
        status, headers, body = await self._request(
            "POST", f"/v1.0/publish/{pubsub}/{topic}", json_body=data,
            params=params)
        if status >= 300:
            self._raise(status, body, context=f"publish {pubsub}/{topic}", headers=headers)

    async def invoke_binding(self, name, operation, data, metadata):
        status, headers, body = await self._request(
            "POST", f"/v1.0/bindings/{name}",
            json_body={"operation": operation, "data": data,
                       "metadata": metadata or {}})
        if status >= 300:
            self._raise(status, body, context=f"binding {name}", headers=headers)
        doc = json.loads(body)
        return BindingResponse(data=doc.get("data"),
                               metadata=doc.get("metadata") or {})

    async def invoke(self, app_id, method_path, http_method, query, headers, body):
        path = f"/v1.0/invoke/{app_id}/method/" + method_path.lstrip("/")
        if query:
            path += f"?{query}"
        return await self._request(http_method, path, headers=headers, data=body)

    async def get_secret(self, store, key):
        status, headers, body = await self._request("GET", f"/v1.0/secrets/{store}/{key}")
        if status >= 300:
            self._raise(status, body, context=f"secret {store}", headers=headers)
        return json.loads(body)

    async def bulk_secrets(self, store):
        status, headers, body = await self._request("GET", f"/v1.0/secrets/{store}/bulk")
        if status >= 300:
            self._raise(status, body, context=f"secret {store}", headers=headers)
        return json.loads(body)

    async def invoke_actor(self, actor_type, actor_id, method, data):
        status, headers, body = await self._request(
            "PUT", f"/v1.0/actors/{actor_type}/{actor_id}/method/{method}",
            json_body=data)
        if status >= 300:
            self._raise(status, body,
                        context=f"actor {actor_type}/{actor_id}.{method}",
                        headers=headers)
        return json.loads(body).get("result") if body else None

    async def register_actor_reminder(self, actor_type, actor_id, name,
                                      due_seconds, period_seconds, data):
        payload = {"dueSeconds": due_seconds, "periodSeconds": period_seconds,
                   "data": data}
        status, headers, body = await self._request(
            "POST", f"/v1.0/actors/{actor_type}/{actor_id}/reminders/{name}",
            json_body=payload)
        if status >= 300:
            self._raise(status, body,
                        context=f"actor reminder {actor_type}/{actor_id}",
                        headers=headers)

    async def unregister_actor_reminder(self, actor_type, actor_id, name):
        status, headers, body = await self._request(
            "DELETE", f"/v1.0/actors/{actor_type}/{actor_id}/reminders/{name}")
        if status >= 300:
            self._raise(status, body,
                        context=f"actor reminder {actor_type}/{actor_id}",
                        headers=headers)

    async def get_actor_state(self, actor_type, actor_id):
        status, headers, body = await self._request(
            "GET", f"/v1.0/actors/{actor_type}/{actor_id}/state")
        if status >= 300:
            self._raise(status, body,
                        context=f"actor state {actor_type}/{actor_id}",
                        headers=headers)
        return json.loads(body)

    async def close(self):
        if self._session is not None:
            await self._session.close()
            self._session = None


class AppClient:
    """The app-facing SDK. Create with ``AppClient.http()`` beside a
    sidecar, or ``AppClient.direct(runtime)`` in-process."""

    def __init__(self, transport: _Transport):
        self._t = transport

    @classmethod
    def http(cls, port: int | None = None, host: str = "127.0.0.1") -> "AppClient":
        if port is None:
            port = int(os.environ.get(PORT_ENV, DEFAULT_SIDECAR_PORT))
        return cls(_HTTPTransport(f"http://{host}:{port}"))

    @classmethod
    def direct(cls, runtime: Runtime) -> "AppClient":
        return cls(_DirectTransport(runtime))

    # -- state -----------------------------------------------------------

    async def save_state(self, store: str, key: str, value: Any, *,
                         etag: str | None = None) -> None:
        item: dict[str, Any] = {"key": key, "value": value}
        if etag is not None:
            item["etag"] = etag
        await self._t.save_state(store, [item])

    async def save_state_bulk(self, store: str, items: list[dict]) -> None:
        await self._t.save_state(store, items)

    async def get_state(self, store: str, key: str) -> Any:
        item = await self._t.get_state(store, key)
        return None if item is None else item.value

    async def get_state_item(self, store: str, key: str) -> StateItem | None:
        return await self._t.get_state(store, key)

    async def delete_state(self, store: str, key: str, *,
                           etag: str | None = None) -> None:
        await self._t.delete_state(store, key, etag)

    async def bulk_get_state(self, store: str, keys: list[str]) -> list[dict]:
        """≙ DaprClient.GetBulkStateAsync: [{key, data?, etag?}]."""
        return await self._t.bulk_get_state(store, keys)

    async def query_state(self, store: str, query: dict) -> dict:
        return await self._t.query_state(store, query)

    async def query_state_values(self, store: str, query: dict) -> list[Any]:
        return [r["data"] for r in (await self._t.query_state(store, query))["results"]]

    async def transact_state(self, store: str, operations: list[dict]) -> None:
        await self._t.transact_state(store, operations)

    # -- pub/sub ---------------------------------------------------------

    async def publish_event(self, pubsub: str, topic: str, data: Any, *,
                            raw: bool = False) -> None:
        await self._t.publish(pubsub, topic, data, raw)

    # -- bindings --------------------------------------------------------

    async def invoke_binding(self, name: str, operation: str, data: Any = None,
                             metadata: dict[str, str] | None = None) -> BindingResponse:
        return await self._t.invoke_binding(name, operation, data, metadata)

    # -- invocation ------------------------------------------------------

    async def invoke_method(self, app_id: str, method_path: str, *,
                            http_method: str = "POST", data: Any = None,
                            query: str = "",
                            headers: dict[str, str] | None = None) -> InvocationResponse:
        headers = dict(headers or {})
        body = b""
        if data is not None:
            body = json.dumps(data).encode()
            headers.setdefault("content-type", "application/json")
        status, resp_headers, resp_body = await self._t.invoke(
            app_id, method_path, http_method, query, headers, body)
        return InvocationResponse(status, resp_headers, resp_body)

    async def invoke_json(self, app_id: str, method_path: str, *,
                          http_method: str = "GET", data: Any = None,
                          query: str = "") -> Any:
        resp = await self.invoke_method(
            app_id, method_path, http_method=http_method, data=data, query=query)
        return resp.raise_for_status().json()

    # -- actors ----------------------------------------------------------

    async def invoke_actor(self, actor_type: str, actor_id: str, method: str,
                           data: Any = None) -> Any:
        """Run one turn on a virtual actor and return its result. The
        runtime routes to the current owner wherever it lives; a 2xx
        means the turn's state changes are durably committed. Raises
        :class:`ActorFencedError` if ownership moved mid-turn — the
        turn was NOT applied; simply retry."""
        return await self._t.invoke_actor(actor_type, actor_id, method, data)

    async def register_actor_reminder(
            self, actor_type: str, actor_id: str, name: str, *,
            due_seconds: float, period_seconds: float | None = None,
            data: Any = None) -> None:
        """Schedule a durable reminder: fires as a turn (``kind ==
        "reminder"``, method = reminder name) after ``due_seconds``,
        then every ``period_seconds`` if periodic. Survives replica
        crashes — whichever replica owns the actor fires it."""
        await self._t.register_actor_reminder(
            actor_type, actor_id, name, due_seconds, period_seconds, data)

    async def unregister_actor_reminder(self, actor_type: str, actor_id: str,
                                        name: str) -> None:
        await self._t.unregister_actor_reminder(actor_type, actor_id, name)

    async def get_actor_state(self, actor_type: str, actor_id: str) -> dict:
        """Diagnostic read of the actor's durable record
        (``{"epoch", "data", "reminders"}``) — not a turn."""
        return await self._t.get_actor_state(actor_type, actor_id)

    # -- secrets ---------------------------------------------------------

    async def get_secret(self, store: str, key: str) -> str:
        return (await self._t.get_secret(store, key))[key]

    async def bulk_secrets(self, store: str) -> dict[str, str]:
        return await self._t.bulk_secrets(store)

    async def close(self) -> None:
        await self._t.close()
