"""Declarative resiliency: timeouts, retries, circuit breakers.

The reference's resilience is inherited from its platform: the Dapr
sidecar's built-in service-invocation retries and mTLS
(docs/aca/03-aca-dapr-integration/index.md:30-38), broker redelivery on
non-2xx (docs/aca/06-aca-dapr-bindingsapi/index.md:55-56), and ACA
restart/scale (SURVEY.md §5.3). Dapr — pinned at 1.14 by the reference
(mkdocs.yml:113-114) — exposes that resilience declaratively as a
``kind: Resiliency`` document: named policies (timeouts, retries,
circuit breakers) bound to targets (apps, components). This package is
the framework's native equivalent: same document shape, applied by the
runtime to service invocation and component (outbound) operations.
"""

from tasksrunner.resiliency.policy import (
    CircuitBreaker,
    ResiliencyPolicies,
    RetrySpec,
    TargetPolicy,
    parse_duration,
)
from tasksrunner.resiliency.spec import (
    ResiliencySpec,
    load_resiliency,
    parse_resiliency,
)

__all__ = [
    "CircuitBreaker",
    "ResiliencyPolicies",
    "ResiliencySpec",
    "RetrySpec",
    "TargetPolicy",
    "load_resiliency",
    "parse_duration",
    "parse_resiliency",
]
