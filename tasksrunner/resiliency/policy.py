"""Policy engine: durations, retry schedules, circuit breakers.

The semantics follow the Dapr resiliency building block the reference's
platform provides (Dapr 1.14, mkdocs.yml:113-114):

* **timeouts** — per-call deadline;
* **retries** — ``constant`` or ``exponential`` backoff, bounded by
  ``maxRetries`` (``-1`` = unlimited) and ``maxInterval``;
* **circuit breakers** — per-target state machine
  (closed → open on ``consecutiveFailures >= N`` → half-open after
  ``timeout`` → closed on probe success / open on probe failure), with
  ``maxRequests`` concurrent probes allowed while half-open.
"""

from __future__ import annotations

import asyncio
import logging
import random
import re
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Iterator

from tasksrunner.errors import CircuitOpenError, ComponentError
from tasksrunner.observability.metrics import metrics

logger = logging.getLogger(__name__)

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ms|s|m|h)")
_UNIT_SECONDS = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}


def parse_duration(raw: str | int | float) -> float:
    """``"500ms"``/``"5s"``/``"1m30s"``/bare seconds → float seconds."""
    if isinstance(raw, (int, float)):
        return float(raw)
    text = str(raw).strip()
    if not text:
        raise ComponentError("empty duration")
    matches = list(_DURATION_RE.finditer(text))
    if matches and "".join(m.group(0) for m in matches) == text.replace(" ", ""):
        return sum(float(m.group(1)) * _UNIT_SECONDS[m.group(2)] for m in matches)
    try:
        return float(text)
    except ValueError:
        raise ComponentError(f"cannot parse duration {raw!r}") from None


@dataclass(frozen=True)
class RetrySpec:
    """A named retry policy (``spec.policies.retries.<name>``)."""

    policy: str = "constant"  # or "exponential"
    #: base delay between attempts
    duration: float = 5.0
    #: backoff cap for the exponential policy
    max_interval: float = 60.0
    #: additional attempts after the first; -1 = unlimited
    max_retries: int = -1
    #: jitter blend in [0, 1]: 0 = the deterministic schedule below
    #: (default, preserves exact historical delays), 1 = fully
    #: decorrelated jitter (AWS style: sleep = min(cap,
    #: uniform(base, prev*3))) so many replicas retrying the same dead
    #: dependency don't synchronize into a thundering herd. Values in
    #: between linearly blend the two.
    jitter: float = 0.0

    def delays(self, rng: random.Random | None = None) -> Iterator[float]:
        if self.jitter and rng is None:
            rng = random.Random()
        n = 0
        prev = self.duration
        while self.max_retries < 0 or n < self.max_retries:
            if self.policy == "exponential":
                base = min(self.duration * (2 ** n), self.max_interval)
            else:
                base = self.duration
            if self.jitter:
                decorrelated = min(self.max_interval,
                                   rng.uniform(self.duration, prev * 3))
                prev = decorrelated
                yield (1.0 - self.jitter) * base + self.jitter * decorrelated
            else:
                yield base
            n += 1


@dataclass(frozen=True)
class CircuitBreakerSpec:
    """A named circuit-breaker definition (``spec.policies.circuitBreakers.<name>``)."""

    name: str
    #: consecutive failures that trip the breaker (``trip:`` expression)
    trip_threshold: int = 5
    #: how long the breaker stays open before allowing probes
    timeout: float = 30.0
    #: probes allowed while half-open
    max_requests: int = 1


_TRIP_RE = re.compile(r"consecutiveFailures\s*(>=|>)\s*(\d+)")


def parse_trip(expr: str) -> int:
    """``"consecutiveFailures >= 5"`` → 5 (the only form Dapr documents
    for its default CB and the only one we support)."""
    m = _TRIP_RE.fullmatch(expr.strip())
    if not m:
        raise ComponentError(
            f"unsupported circuit-breaker trip expression {expr!r} "
            "(expected 'consecutiveFailures >= N')")
    threshold = int(m.group(2))
    return threshold + 1 if m.group(1) == ">" else threshold


class CircuitBreaker:
    """Per-target breaker state machine. One instance per (policy,
    target) pair, shared by every call to that target, so failures
    observed by one caller protect the rest."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    #: gauge encoding for resiliency_breaker_state{policy,target}
    _STATE_VALUES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def __init__(self, spec: CircuitBreakerSpec, *, target: str = ""):
        self.spec = spec
        self.target = target
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self._opened_at = 0.0
        self._half_open_inflight = 0
        self._publish_state()

    def _publish_state(self) -> None:
        # 0=closed, 1=half-open, 2=open — admin surfaces read this to
        # show WHY traffic toward a target is being shed
        metrics.set_gauge("resiliency_breaker_state",
                          self._STATE_VALUES[self.state],
                          policy=self.spec.name, target=self.target)

    def before_call(self) -> None:
        """Gate a call; raises ``CircuitOpenError`` when rejected."""
        if self.state == self.OPEN:
            if time.monotonic() - self._opened_at >= self.spec.timeout:
                self.state = self.HALF_OPEN
                self._half_open_inflight = 0
                self._publish_state()
                logger.info("circuit %s[%s] half-open (probing)",
                            self.spec.name, self.target)
            else:
                raise CircuitOpenError(
                    f"circuit {self.spec.name!r} open for target {self.target!r}")
        if self.state == self.HALF_OPEN:
            if self._half_open_inflight >= self.spec.max_requests:
                raise CircuitOpenError(
                    f"circuit {self.spec.name!r} half-open, probe limit reached "
                    f"for target {self.target!r}")
            self._half_open_inflight += 1

    def record_success(self) -> None:
        if self.state != self.CLOSED:
            logger.info("circuit %s[%s] closed", self.spec.name, self.target)
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self._half_open_inflight = 0
        self._publish_state()

    def release_probe(self) -> None:
        """A half-open probe ended without a verdict (e.g. the caller
        was cancelled): free its slot so the breaker can't wedge with
        all probes leaked."""
        if self.state == self.HALF_OPEN and self._half_open_inflight > 0:
            self._half_open_inflight -= 1

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        should_trip = (
            self.state == self.HALF_OPEN
            or self.consecutive_failures >= self.spec.trip_threshold
        )
        if should_trip and self.state != self.OPEN:
            self.state = self.OPEN
            self._opened_at = time.monotonic()
            self._publish_state()
            logger.warning("circuit %s[%s] OPEN after %d consecutive failures",
                           self.spec.name, self.target, self.consecutive_failures)


@dataclass
class TargetPolicy:
    """The resolved policy set for one target (app or component)."""

    target: str
    timeout: float | None = None
    retry: RetrySpec | None = None
    breaker: CircuitBreaker | None = None
    #: "perAttempt" (historical default: each attempt gets the full
    #: timeout, so a 3-retry policy with a 5s timeout can hold a caller
    #: for 20s+) or "total": the timeout is an overall budget across
    #: attempts AND backoff sleeps.
    timeout_policy: str = "perAttempt"

    async def execute(
        self,
        fn: Callable[[], Awaitable],
        *,
        retriable: tuple[type[BaseException], ...] = (OSError,),
    ):
        """Run ``fn`` under this policy.

        ``retriable`` exceptions (plus timeouts) consume retry budget;
        anything else propagates immediately but still counts as a
        breaker failure. ``CircuitOpenError`` raised by the gate is
        never retried here — fail fast is the point of the breaker.
        """
        delays = self.retry.delays() if self.retry else iter(())
        deadline = (
            time.monotonic() + self.timeout
            if self.timeout is not None and self.timeout_policy == "total"
            else None)

        def _budget_error(cause: BaseException | None = None) -> TimeoutError:
            err = TimeoutError(
                f"call to {self.target!r} exceeded {self.timeout}s "
                "total budget")
            if cause is not None:
                err.__cause__ = cause
            return err

        while True:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise _budget_error()
            if self.breaker is not None:
                self.breaker.before_call()
            try:
                if remaining is not None:
                    result = await asyncio.wait_for(fn(), remaining)
                elif self.timeout is not None:
                    result = await asyncio.wait_for(fn(), self.timeout)
                else:
                    result = await fn()
            except (asyncio.TimeoutError, *retriable) as exc:
                if self.breaker is not None:
                    self.breaker.record_failure()
                delay = next(delays, None)
                if delay is None:
                    metrics.inc("resiliency_retry_exhausted_total",
                                target=self.target)
                    if isinstance(exc, asyncio.TimeoutError):
                        raise TimeoutError(
                            f"call to {self.target!r} exceeded "
                            f"{self.timeout}s timeout") from exc
                    raise
                # honor a server-sent Retry-After (429 shed / 503
                # breaker): hammering a replica that just said "stay
                # away" defeats the shed. Clamped to the policy's
                # max_interval, and the total-budget check below still
                # wins — the hint stretches a delay, never the budget.
                hint = getattr(exc, "retry_after", None)
                if hint:
                    delay = max(delay, float(hint))
                    if self.retry is not None:
                        delay = min(delay, self.retry.max_interval)
                if deadline is not None and \
                        time.monotonic() + delay >= deadline:
                    # sleeping through the backoff would blow the
                    # budget — surface exhaustion NOW, not after it
                    metrics.inc("resiliency_retry_exhausted_total",
                                target=self.target)
                    raise _budget_error(exc)
                metrics.inc("resiliency_retry_total", target=self.target)
                logger.warning("retrying %s in %.3fs after %r",
                               self.target, delay, exc)
                await asyncio.sleep(delay)
                continue
            except Exception:
                if self.breaker is not None:
                    self.breaker.record_failure()
                raise
            except BaseException:
                # cancellation is not a verdict on the target's health —
                # free the probe slot instead of leaking it (a leaked
                # slot would pin the breaker half-open forever)
                if self.breaker is not None:
                    self.breaker.release_probe()
                raise
            if self.breaker is not None:
                self.breaker.record_success()
            return result


@dataclass
class _TargetRef:
    timeout: str | None = None
    retry: str | None = None
    circuit_breaker: str | None = None
    #: "perAttempt" | "total" (see TargetPolicy.timeout_policy)
    timeout_policy: str = "perAttempt"


@dataclass
class _ParsedSpec:
    """One parsed Resiliency document (see spec.py for the YAML side)."""

    name: str
    scopes: list[str] = field(default_factory=list)
    timeouts: dict[str, float] = field(default_factory=dict)
    retries: dict[str, RetrySpec] = field(default_factory=dict)
    breakers: dict[str, CircuitBreakerSpec] = field(default_factory=dict)
    app_targets: dict[str, _TargetRef] = field(default_factory=dict)
    component_targets: dict[str, dict[str, _TargetRef]] = field(default_factory=dict)

    def in_scope(self, app_id: str | None) -> bool:
        if not self.scopes or app_id is None:
            return True
        return app_id in self.scopes


class ResiliencyPolicies:
    """The runtime-facing view: merged in-scope specs with per-target
    breaker instances that persist across calls."""

    def __init__(self, specs: list[_ParsedSpec], *, app_id: str | None = None):
        self.specs = [s for s in specs if s.in_scope(app_id)]
        self._breakers: dict[tuple[str, str], CircuitBreaker] = {}
        self._cache: dict[tuple[str, str, str], TargetPolicy | None] = {}

    def for_app(self, app_id: str) -> TargetPolicy | None:
        """Policy applied to service invocation toward ``app_id``."""
        return self._resolve("apps", app_id, "outbound")

    def for_component(self, name: str, direction: str = "outbound") -> TargetPolicy | None:
        """Policy applied to component operations on ``name``."""
        return self._resolve("components", name, direction)

    def _resolve(self, kind: str, name: str, direction: str) -> TargetPolicy | None:
        cache_key = (kind, name, direction)
        if cache_key in self._cache:
            return self._cache[cache_key]
        policy = None
        for spec in self.specs:
            if kind == "apps":
                ref = spec.app_targets.get(name)
            else:
                ref = (spec.component_targets.get(name) or {}).get(direction)
            if ref is None:
                continue
            timeout = spec.timeouts.get(ref.timeout) if ref.timeout else None
            if ref.timeout and timeout is None:
                raise ComponentError(
                    f"resiliency {spec.name!r}: unknown timeout {ref.timeout!r}")
            retry = spec.retries.get(ref.retry) if ref.retry else None
            if ref.retry and retry is None:
                raise ComponentError(
                    f"resiliency {spec.name!r}: unknown retry {ref.retry!r}")
            breaker = None
            if ref.circuit_breaker:
                cb_spec = spec.breakers.get(ref.circuit_breaker)
                if cb_spec is None:
                    raise ComponentError(
                        f"resiliency {spec.name!r}: unknown circuit breaker "
                        f"{ref.circuit_breaker!r}")
                bk = (cb_spec.name, f"{kind}/{name}/{direction}")
                breaker = self._breakers.setdefault(
                    bk, CircuitBreaker(cb_spec, target=name))
            policy = TargetPolicy(
                target=name, timeout=timeout, retry=retry, breaker=breaker,
                timeout_policy=ref.timeout_policy)
            break  # first in-scope spec naming the target wins
        self._cache[cache_key] = policy
        return policy
