"""Parse ``kind: Resiliency`` YAML documents.

Document shape (the Dapr 1.14 resiliency schema the reference's
platform understands; the reference itself relies on the sidecar's
built-in defaults, SURVEY.md §5.3):

.. code-block:: yaml

    apiVersion: dapr.io/v1alpha1
    kind: Resiliency
    metadata:
      name: tasks-resiliency
    scopes: [tasksmanager-frontend-webapp]     # optional
    spec:
      policies:
        timeouts:
          fast: 500ms
        retries:
          important:
            policy: exponential
            duration: 200ms
            maxInterval: 5s
            maxRetries: 3
        circuitBreakers:
          simpleCB:
            maxRequests: 1
            timeout: 30s
            trip: consecutiveFailures >= 5
      targets:
        apps:
          tasksmanager-backend-api:
            timeout: fast
            retry: important
            circuitBreaker: simpleCB
        components:
          statestore:
            outbound:
              retry: important

These files live in the same resources directory as components; the
component loader skips them and ``load_resiliency`` collects them.
"""

from __future__ import annotations

import pathlib
from typing import Any, Mapping

import yaml

from tasksrunner.errors import ComponentError
from tasksrunner.resiliency.policy import (
    CircuitBreakerSpec,
    RetrySpec,
    _ParsedSpec,
    _TargetRef,
    parse_duration,
    parse_trip,
)

ResiliencySpec = _ParsedSpec

_YAML_SUFFIXES = {".yaml", ".yml"}


def is_resiliency_doc(doc: Any) -> bool:
    return isinstance(doc, Mapping) and doc.get("kind") == "Resiliency"


def _parse_target_ref(raw: Mapping[str, Any], *, where: str) -> _TargetRef:
    if not isinstance(raw, Mapping):
        raise ComponentError(f"{where}: target must be a mapping")
    timeout_policy = str(raw.get("timeoutPolicy", "perAttempt"))
    if timeout_policy not in ("perAttempt", "total"):
        raise ComponentError(
            f"{where}: timeoutPolicy must be 'perAttempt' or 'total', "
            f"not {timeout_policy!r}")
    return _TargetRef(
        timeout=raw.get("timeout"),
        retry=raw.get("retry"),
        circuit_breaker=raw.get("circuitBreaker"),
        timeout_policy=timeout_policy,
    )


def parse_resiliency(doc: Mapping[str, Any], *, source: str | None = None) -> ResiliencySpec:
    where = source or "resiliency"
    if not is_resiliency_doc(doc):
        raise ComponentError(f"{where}: not a Resiliency document")
    meta = doc.get("metadata") or {}
    name = str(meta.get("name") or "resiliency")
    spec = doc.get("spec") or {}
    policies = spec.get("policies") or {}

    timeouts = {
        str(k): parse_duration(v)
        for k, v in (policies.get("timeouts") or {}).items()
    }

    retries: dict[str, RetrySpec] = {}
    for rname, raw in (policies.get("retries") or {}).items():
        if not isinstance(raw, Mapping):
            raise ComponentError(f"{where}: retry {rname!r} must be a mapping")
        jitter = float(raw.get("jitter", 0.0))
        if not 0.0 <= jitter <= 1.0:
            raise ComponentError(
                f"{where}: retry {rname!r}: jitter must be in [0, 1]")
        retries[str(rname)] = RetrySpec(
            policy=str(raw.get("policy", "constant")),
            duration=parse_duration(raw.get("duration", "5s")),
            max_interval=parse_duration(raw.get("maxInterval", "60s")),
            max_retries=int(raw.get("maxRetries", -1)),
            jitter=jitter,
        )

    breakers: dict[str, CircuitBreakerSpec] = {}
    for bname, raw in (policies.get("circuitBreakers") or {}).items():
        if not isinstance(raw, Mapping):
            raise ComponentError(f"{where}: circuitBreaker {bname!r} must be a mapping")
        breakers[str(bname)] = CircuitBreakerSpec(
            name=str(bname),
            trip_threshold=parse_trip(str(raw.get("trip", "consecutiveFailures >= 5"))),
            timeout=parse_duration(raw.get("timeout", "30s")),
            max_requests=int(raw.get("maxRequests", 1)),
        )

    targets = spec.get("targets") or {}
    app_targets = {
        str(app): _parse_target_ref(raw, where=where)
        for app, raw in (targets.get("apps") or {}).items()
    }
    component_targets: dict[str, dict[str, _TargetRef]] = {}
    for comp, raw in (targets.get("components") or {}).items():
        if not isinstance(raw, Mapping):
            raise ComponentError(f"{where}: component target {comp!r} must be a mapping")
        directions: dict[str, _TargetRef] = {}
        for direction in ("outbound", "inbound"):
            if direction in raw:
                directions[direction] = _parse_target_ref(raw[direction], where=where)
        if not directions:
            # bare refs apply outbound (the common case)
            directions["outbound"] = _parse_target_ref(raw, where=where)
        component_targets[str(comp)] = directions

    scopes = doc.get("scopes") or []
    if not isinstance(scopes, list) or not all(isinstance(s, str) for s in scopes):
        raise ComponentError(f"{where}: scopes must be a list of app-ids")

    # reject dangling policy references at load time — a typo must fail
    # the host's startup, not the first request months later
    all_refs = list(app_targets.items()) + [
        (comp, ref)
        for comp, dirs in component_targets.items()
        for ref in dirs.values()
    ]
    for target, ref in all_refs:
        if ref.timeout and ref.timeout not in timeouts:
            raise ComponentError(
                f"{where}: target {target!r} references unknown timeout {ref.timeout!r}")
        if ref.retry and ref.retry not in retries:
            raise ComponentError(
                f"{where}: target {target!r} references unknown retry {ref.retry!r}")
        if ref.circuit_breaker and ref.circuit_breaker not in breakers:
            raise ComponentError(
                f"{where}: target {target!r} references unknown circuit breaker "
                f"{ref.circuit_breaker!r}")

    return ResiliencySpec(
        name=name,
        scopes=list(scopes),
        timeouts=timeouts,
        retries=retries,
        breakers=breakers,
        app_targets=app_targets,
        component_targets=component_targets,
    )


def load_resiliency(resources_path: str | pathlib.Path) -> list[ResiliencySpec]:
    """Collect every ``kind: Resiliency`` document under ``resources_path``."""
    root = pathlib.Path(resources_path)
    if not root.is_dir():
        return []
    specs: list[ResiliencySpec] = []
    for path in sorted(root.iterdir()):
        if path.suffix.lower() not in _YAML_SUFFIXES or not path.is_file():
            continue
        try:
            docs = list(yaml.safe_load_all(path.read_text()))
        except (OSError, yaml.YAMLError) as exc:
            raise ComponentError(f"cannot read {path}: {exc}") from exc
        for doc in docs:
            if is_resiliency_doc(doc):
                specs.append(parse_resiliency(doc, source=str(path)))
    return specs
