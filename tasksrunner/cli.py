"""Command-line interface.

Commands mirror the reference's local workflow surface:

* ``tasksrunner host``    — one service: app server + sidecar in one
  process (what the orchestrator spawns per replica)
* ``tasksrunner serve``   — app server only (pair with ``sidecar`` for
  the fully decoupled two-process layout ``dapr run`` uses)
* ``tasksrunner sidecar`` — sidecar only, attaching to a running app
  (≙ ``dapr run --app-id X --app-port P --dapr-http-port D``,
  snippets/dapr-run-backend-api.md:4-16)
* ``tasksrunner run``     — multi-app orchestrator from a run config
  (≙ the VS Code compound launcher), with KEDA-style autoscaling
* ``tasksrunner ps``      — live status of registered apps
  (≙ ``dapr list`` / ``az containerapp replica list``,
  docs/aca/09-aca-autoscale-keda/index.md:170-200)
* ``tasksrunner components`` — validate/list a resources directory
  (≙ the sidecar's component loading report)
* ``tasksrunner invoke / publish / state / secret`` — one-shot probes
  against a running app's sidecar (≙ ``dapr invoke`` / ``dapr
  publish`` / the workshop's curl checkpoints,
  docs/aca/04-aca-dapr-stateapi/index.md:41-75)
* ``tasksrunner stop``    — SIGTERM every replica of a registered app
  (≙ ``dapr stop``)
* ``tasksrunner traces``  — transaction search / span tree / service
  map over the span store, plus ``traces query`` for read-only SQL
  (≙ App Insights transaction search + Log Analytics, docs module 8)
* ``tasksrunner logs / metrics / restart / scale / update / revisions
  / dlq`` — the ``az containerapp`` operations surface against the
  orchestrator's admin API (docs module 14)
"""

from __future__ import annotations

import argparse
import asyncio
import importlib
import json
import logging
import os
import sys

from tasksrunner.app import App


def _load_factory(spec: str):
    """Import "pkg.module:factory" and return the factory/App."""
    from tasksrunner.errors import TasksRunnerError

    module_name, _, attr = spec.partition(":")
    try:
        module = importlib.import_module(module_name)
    except ImportError as exc:
        # a typo'd --module arg is an operator error, not a crash:
        # one clean line instead of the runpy import traceback
        raise TasksRunnerError(
            f"cannot import app module {module_name!r} (from {spec!r}): "
            f"{exc}. The form is pkg.module:factory, resolved on "
            f"PYTHONPATH from the current directory") from exc
    try:
        return getattr(module, attr or "make_app")
    except AttributeError as exc:
        raise TasksRunnerError(
            f"module {module_name!r} has no attribute "
            f"{attr or 'make_app'!r} (from {spec!r})") from exc


def _make_app(spec: str) -> App:
    target = _load_factory(spec)
    app = target() if callable(target) and not isinstance(target, App) else target
    if not isinstance(app, App):
        raise SystemExit(f"{spec} did not produce a tasksrunner.App")
    return app


def _cmd_host(args) -> None:
    from tasksrunner.hosting import AppHost
    from tasksrunner.observability.logging import configure_logging

    import os

    from tasksrunner.observability.spans import ENV_VAR, configure_spans

    app = _make_app(args.module)
    if args.app_id:
        app.app_id = args.app_id
    configure_logging(app.app_id, level=getattr(logging, args.log_level.upper()))
    # span recording on by default for hosted services (set
    # TASKSRUNNER_TRACE_DB= empty to disable)
    configure_spans(app.app_id,
                    os.environ.get(ENV_VAR, ".tasksrunner/traces.db") or None)
    host = AppHost(
        app,
        components_path=args.components,
        app_port=args.app_port,
        sidecar_port=args.sidecar_port,
        bind=args.host,
        registry_file=args.registry_file,
        register=not args.no_register,
    )

    async def main():
        await host.start()
        print(f"ready app={app.app_id} app_port={host.app_port} "
              f"sidecar_port={host.sidecar_port}", flush=True)
        try:
            await asyncio.Event().wait()
        finally:
            # Ctrl-C cancels this task; the stop must still complete
            await asyncio.shield(host.stop())

    _run_until_interrupt(main())


def _cmd_serve(args) -> None:
    from aiohttp import web
    from tasksrunner.client import AppClient
    from tasksrunner.hosting import _access_log, build_app_server
    from tasksrunner.observability.logging import configure_logging

    app = _make_app(args.module)
    configure_logging(app.app_id, level=getattr(logging, args.log_level.upper()))
    app.client = AppClient.http(args.sidecar_port)

    async def main():
        runner = web.AppRunner(build_app_server(app), access_log=_access_log())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", args.port)
        await site.start()
        port = runner.addresses[0][1]
        await app.startup()
        print(f"ready app={app.app_id} app_port={port}", flush=True)
        try:
            await asyncio.Event().wait()
        finally:
            await asyncio.shield(app.shutdown())
            await asyncio.shield(runner.cleanup())

    _run_until_interrupt(main())


def _cmd_sidecar(args) -> None:
    from tasksrunner.component.loader import load_components
    from tasksrunner.component.registry import ComponentRegistry
    from tasksrunner.invoke.resolver import AppAddress, NameResolver
    from tasksrunner.observability.logging import configure_logging
    from tasksrunner.runtime import HTTPAppChannel, Runtime
    from tasksrunner.sidecar import Sidecar

    configure_logging(f"{args.app_id}-sidecar",
                      level=getattr(logging, args.log_level.upper()))
    specs = load_components(args.components) if args.components else []
    resolver = NameResolver(registry_file=args.registry_file)

    async def main():
        registry = ComponentRegistry(specs, app_id=args.app_id)
        runtime = Runtime(args.app_id, registry, resolver=resolver,
                          app_channel=HTTPAppChannel("127.0.0.1", args.app_port))
        sidecar = Sidecar(runtime, port=args.port)
        await sidecar.start()
        resolver.register(AppAddress(app_id=args.app_id, host="127.0.0.1",
                                     sidecar_port=sidecar.port,
                                     app_port=args.app_port,
                                     mesh_port=sidecar.mesh_port))
        runtime.kick_mesh_prewarm()
        print(f"ready app={args.app_id} sidecar_port={sidecar.port}", flush=True)
        try:
            await asyncio.Event().wait()
        finally:
            resolver.unregister(args.app_id, pid=os.getpid(),
                                sidecar_port=sidecar.port)
            await asyncio.shield(sidecar.stop())

    _run_until_interrupt(main())


def _cmd_run(args) -> None:
    from tasksrunner.observability.logging import configure_logging
    from tasksrunner.orchestrator.config import load_run_config
    from tasksrunner.orchestrator.run import run_from_config

    configure_logging("orchestrator",
                      level=getattr(logging, args.log_level.upper()))
    config = load_run_config(args.config)
    if args.standby:
        config.standby = True
    if args.no_adopt:
        config.adopt = False
    _run_until_interrupt(run_from_config(config))


def _cmd_deploy(args) -> None:
    from tasksrunner.deploy import (
        apply_manifest,
        load_manifest,
        validate_manifest,
        what_if,
    )
    from tasksrunner.deploy.plan import destroy

    manifest = load_manifest(args.manifest)
    if args.action == "validate":
        problems = validate_manifest(manifest)
        if problems:
            for p in problems:
                print(f"ERROR: {p}")
            raise SystemExit(1)
        print(f"manifest {manifest.name!r} is valid "
              f"({len(manifest.apps)} apps, {len(manifest.components)} components)")
    elif args.action == "what-if":
        preview = what_if(manifest)
        if not preview["valid"]:
            for p in preview["problems"]:
                print(f"ERROR: {p}")
            raise SystemExit(1)
        if not preview["changes"]:
            print("no changes — recorded state matches the manifest")
        for change in preview["changes"]:
            if change["op"] == "modify":
                print(f"~ {change['path']}: {change['from']!r} -> {change['to']!r}")
            else:
                sign = "+" if change["op"] == "create" else "-"
                print(f"{sign} {change['path'] or manifest.name}")
    elif args.action == "apply":
        result = apply_manifest(manifest)
        print(f"applied {len(result['changes'])} change(s)")
        print(f"run config: {result['run_config']}")
        print(f"state:      {result['state']}")
        print(f"start with: python -m tasksrunner run {result['run_config']}")
    elif args.action == "down":
        if destroy(manifest):
            print(f"environment {manifest.name!r} state removed")
        else:
            print(f"environment {manifest.name!r} had no recorded state")


def _span_split(span: dict) -> str:
    """Render a span's queue-wait/service split when the lane recorded
    one (batched hops: state writes, ML batches)."""
    attrs = span.get("attrs")
    if isinstance(attrs, str):
        try:
            attrs = json.loads(attrs)
        except ValueError:
            return ""
    if not isinstance(attrs, dict) or "queue_wait" not in attrs:
        return ""
    try:
        return (f"  [wait {float(attrs['queue_wait']) * 1000:.1f}ms"
                f" / svc {float(attrs.get('service', 0.0)) * 1000:.1f}ms]")
    except (TypeError, ValueError):
        return ""


def _cmd_traces(args) -> None:
    import pathlib
    import sys

    from tasksrunner.observability.spans import (
        assemble_trace, critical_path, list_traces, service_map,
    )

    # --db accepts a comma-separated list: each replica records into
    # its own span DB, and show/critical assemble across all of them
    dbs = [p.strip() for p in (args.db or "").split(",") if p.strip()]
    existing = [p for p in dbs if pathlib.Path(p).is_file()]
    if not existing:
        # exit 2 = "nothing to inspect", distinct from a failed query
        # against a real database (and never a raw sqlite traceback)
        print(f"no trace database at {args.db or '(unset)'} "
              "(services record to .tasksrunner/traces.db by default)",
              file=sys.stderr)
        raise SystemExit(2)
    db = existing[0]

    if args.action == "list":
        rows = list_traces(db, limit=args.limit)
        if not rows:
            print("no traces recorded")
            return
        for r in rows:
            import datetime as dt
            ts = dt.datetime.fromtimestamp(r["started"]).strftime("%H:%M:%S")
            print(f"{r['trace_id'][:16]}  {ts}  {r['spans']:>3} spans  "
                  f"{(r['wall'] or 0) * 1000:7.1f} ms  {r['root']}")
    elif args.action == "show":
        if not args.trace_id:
            raise SystemExit("show needs a trace id (prefix ok)")
        spans = assemble_trace(existing, args.trace_id)
        if not spans:
            raise SystemExit(f"no spans for trace {args.trace_id!r}")
        t0 = spans[0]["start"]
        # real tree depth from parent ids (falls back to 0 for roots /
        # spans whose parent wasn't recorded in this process set)
        by_id = {s["span_id"]: s for s in spans}

        def depth(s, seen=()):
            parent = s.get("parent_id")
            if not parent or parent not in by_id or parent in seen:
                return 0
            return 1 + depth(by_id[parent], (*seen, s["span_id"]))

        for s in spans:
            offset = (s["start"] - t0) * 1000
            indent = "  " * depth(s)
            print(f"{offset:8.1f}ms {s['duration']*1000:7.1f}ms  "
                  f"{indent}[{s['role']}] {s['kind']:<8} {s['name']} "
                  f"({s['status']}){_span_split(s)}")
    elif args.action == "critical":
        if not args.trace_id:
            raise SystemExit("critical needs a trace id (prefix ok)")
        spans = assemble_trace(existing, args.trace_id)
        if not spans:
            raise SystemExit(f"no spans for trace {args.trace_id!r}")
        hops = critical_path(spans)
        if not hops:
            raise SystemExit(f"no rooted path in trace {args.trace_id!r}")
        # blame denominator is the CHAIN's wall time, not the root
        # span's duration: an async tail (a consumer hop that starts
        # after the root responded) legitimately extends the chain past
        # the root, and broker transit shows up as unaccounted time
        # instead of pushing the ledger over 100%
        total = (max(h["start"] + h["duration"] for h in hops)
                 - hops[0]["start"])
        print(f"critical path: {len(hops)} hops over {len(spans)} spans, "
              f"root {hops[0]['name']!r}, wall {total * 1000:.1f} ms")
        t0 = hops[0]["start"]
        for hop in hops:
            split = ""
            if "queue_wait" in hop:
                split = (f"  (wait {hop['queue_wait'] * 1000:.1f}ms"
                         f" / svc {hop.get('service', 0.0) * 1000:.1f}ms)")
            print(f"{(hop['start'] - t0) * 1000:8.1f}ms "
                  f"self {hop['self_time'] * 1000:7.1f}ms  "
                  f"[{hop['role']}] {hop['kind']:<8} {hop['name']}{split}")
        accounted = sum(h["self_time"] for h in hops)
        pct = (accounted / total * 100.0) if total > 0 else 100.0
        print(f"blame accounted: {accounted * 1000:.1f} ms of "
              f"{total * 1000:.1f} ms ({pct:.0f}%)")
    elif args.action == "query":
        # the local Log-Analytics pane (≙ the reference's Kusto queries
        # over App Insights tables, docs module 8): read-only SQL
        # straight over the span store. Opened with mode=ro so no
        # query — however creative — can mutate telemetry.
        if not args.trace_id:
            raise SystemExit(
                "query needs SQL, e.g. tasksrunner traces query "
                "\"SELECT role, COUNT(*) FROM spans GROUP BY role\"")
        import sqlite3 as _sqlite3

        from tasksrunner.observability.spans import _connect_ro
        conn = _connect_ro(db)
        try:
            cur = conn.execute(args.trace_id)
            cols = [d[0] for d in cur.description or []]
            rows = cur.fetchall()
        except _sqlite3.Error as exc:
            raise SystemExit(f"query failed: {exc}")
        finally:
            conn.close()
        if cols:
            print("\t".join(cols))
        for row in rows:
            print("\t".join(
                f"{v:.3f}" if isinstance(v, float) else str(v) for v in row))
    elif args.action == "map":
        edges = service_map(db)
        if not edges:
            print("no client/producer spans recorded")
            return
        if getattr(args, "mermaid", False):
            # paste-ready App-Map diagram (mkdocs-material renders
            # mermaid fences — the docs' architecture diagrams use the
            # same notation)
            ids: dict[str, str] = {}

            def node(name: str) -> str:
                # sanitized ids can collide ("ps/saved" vs "ps-saved");
                # keep them unique per distinct NAME so the diagram
                # never silently merges two services
                if name not in ids:
                    base = "n" + "".join(
                        c if c.isalnum() else "_" for c in name)
                    ids[name] = (f"{base}_{len(ids)}"
                                 if base in set(ids.values()) else base)
                return ids[name]

            def label(text) -> str:
                # mermaid "..." labels: double quotes break the parser
                return str(text).replace('"', "#quot;")

            print("graph LR")
            for e in edges:
                style = "-.->" if e["kind"] == "producer" else "-->"
                print(f'  {node(e["from"])}["{label(e["from"])}"] '
                      f'{style}|"{e["calls"]} calls, avg {e["avg_ms"]} ms"| '
                      f'{node(e["to"])}["{label(e["to"])}"]')
            return
        for e in edges:
            print(f"{e['from']:<36} --{e['kind']}--> {e['to']:<42} "
                  f"{e['calls']:>5} calls  avg {e['avg_ms']} ms")


def _cmd_flightrec(args) -> None:
    """Inspect black-box flight-recorder dumps (the post-mortem ring
    each process writes on shed entry, slow exemplars, and unclean
    shutdown)."""
    import datetime as dt

    from tasksrunner.observability import flightrec

    if args.dump:
        try:
            payload = flightrec.read_dump(args.dump)
        except (OSError, ValueError) as exc:
            raise SystemExit(f"cannot read dump {args.dump!r}: {exc}")
        ts = dt.datetime.fromtimestamp(payload.get("ts") or 0)
        print(f"{payload.get('role')} pid {payload.get('pid')} — "
              f"{payload.get('reason')} at {ts:%H:%M:%S}  "
              f"detail={payload.get('detail')}")
        gauges = payload.get("gauges") or {}
        if gauges:
            print("gauges at dump: " + "  ".join(
                f"{k}={v:.3f}" for k, v in sorted(gauges.items())))
        entries = payload.get("entries") or []
        for e in entries[-args.limit:]:
            ets = dt.datetime.fromtimestamp(e.get("ts") or 0)
            trace = (e.get("trace") or "")[:16] or "-"
            print(f"{ets:%H:%M:%S}.{ets.microsecond // 1000:03d}  "
                  f"{(e.get('dur') or 0) * 1000:7.1f}ms  "
                  f"({e.get('status')}) {e.get('name')}  trace {trace}"
                  + (f"  gauges {e['gauges']}" if e.get("gauges") else ""))
        return
    rows = flightrec.list_dumps(args.dir)
    if not rows:
        print(f"no flight-recorder dumps in {args.dir} "
              "(dumps appear on shed entry, slow exemplars, and "
              "unclean shutdown)")
        return
    for r in rows:
        ts = dt.datetime.fromtimestamp(r.get("ts") or 0)
        print(f"{ts:%H:%M:%S}  {r['reason']:<18} {r['role']} "
              f"pid {r['pid']}  {r['entries']:>4} entries  {r['path']}")
    print(f"# inspect one: tasksrunner flightrec --dump {rows[0]['path']}")


def _cmd_ps(args) -> None:
    """Live status of registered apps (≙ `dapr list` + `az containerapp
    replica list`, docs/aca/09-aca-autoscale-keda/index.md:170-200):
    reads the name-registry file, then probes each sidecar for health
    and metadata."""
    import json as json_mod
    import os
    import pathlib
    import time

    from tasksrunner.invoke.resolver import NameResolver
    from tasksrunner.security import TOKEN_ENV, TOKEN_HEADER

    registry_path = pathlib.Path(args.registry_file)
    if not registry_path.is_file():
        raise SystemExit(f"no registry file at {registry_path} "
                         "(is anything running? check run.yaml's registry_file)")
    resolver = NameResolver(registry_file=registry_path)
    app_ids = resolver.known_apps()
    if not app_ids:
        print("no apps registered")
        return

    async def probe_all():
        import aiohttp

        timeout = aiohttp.ClientTimeout(total=2.0)
        headers = {}
        token = os.environ.get(TOKEN_ENV)
        if token:
            headers[TOKEN_HEADER] = token

        net_errors = (OSError, asyncio.TimeoutError, aiohttp.ClientError)

        async def probe_app(s, app_id):
            """One row per registered replica (≙ `az containerapp
            replica list`); scale-out replicas show as app-id·N."""
            replicas = resolver.resolve_all(app_id)
            if not replicas:
                # unregistered between listing and probing — report it,
                # don't abort the other rows
                return [{"app_id": app_id, "pid": None, "app_port": None,
                         "sidecar_port": None, "host": None,
                         "up_seconds": None, "health": "gone",
                         "components": None, "subscriptions": None,
                         "actors": None}]
            return await asyncio.gather(
                *(probe(s, app_id, addr, idx, len(replicas))
                  for idx, addr in enumerate(replicas)))

        async def probe(s, app_id, addr, idx, n_replicas):
            # app_id stays the clean machine-readable key (--json
            # consumers filter on it); the replica ordinal is its own
            # field and only the human-readable table fuses them
            row = {
                "app_id": app_id,
                "replica": idx if n_replicas > 1 else None,
                "pid": addr.pid,
                "app_port": addr.app_port,
                "sidecar_port": addr.sidecar_port,
                "host": addr.host,
                "up_seconds": (round(time.time() - addr.registered_at)
                               if addr.registered_at else None),
                "health": "down",
                "components": None,
                "subscriptions": None,
                "actors": None,
            }
            # a dead LOCAL pid is stale registry debris (SIGKILL leaves
            # entries behind) — report it as such instead of probing
            # ports a NEW incarnation may have reclaimed, which would
            # show the ghost as healthy
            if NameResolver.local_pid_dead(addr.host, addr.pid,
                                           addr.registered_at):
                row["health"] = "stale"
                return row
            try:
                async with s.get(f"{addr.base_url}/v1.0/healthz") as r:
                    row["health"] = "ok" if r.status < 500 else "unhealthy"
            except net_errors:
                return row
            # the sidecar's healthz is pure liveness; the app's own
            # /healthz (possibly user-registered) is the real signal —
            # same endpoint the orchestrator's liveness probe uses
            if addr.app_port:
                try:
                    async with s.get(
                        f"http://{addr.host}:{addr.app_port}/healthz") as r:
                        if r.status >= 500:
                            row["health"] = "unhealthy"
                except net_errors:
                    row["health"] = "app-down"
            try:
                async with s.get(f"{addr.base_url}/v1.0/metadata",
                                 headers=headers) as r:
                    if r.status == 200:
                        meta = await r.json()
                        row["components"] = len(meta.get("components") or [])
                        row["subscriptions"] = len(
                            meta.get("subscriptions") or [])
                        # activations this replica owns ("-" when the
                        # actor gate is off or the app hosts no types)
                        actors = meta.get("actors")
                        if actors is not None:
                            row["actors"] = sum(
                                (actors.get("owned") or {}).values())
                    elif r.status == 401:
                        row["components"] = "auth"
                        row["subscriptions"] = "auth"
            except net_errors:
                pass
            return row

        async with aiohttp.ClientSession(timeout=timeout) as session:
            groups = await asyncio.gather(
                *(probe_app(session, a) for a in app_ids))
            return [row for group in groups for row in group]

    rows = asyncio.run(probe_all())
    any_down = any(r["health"] in ("down", "app-down", "gone") for r in rows)
    if args.json:
        print(json_mod.dumps(rows, indent=2))
        if any_down:
            raise SystemExit(2)
        return

    def fmt_up(seconds):
        if seconds is None:
            return "-"
        m, s = divmod(int(seconds), 60)
        h, m = divmod(m, 60)
        return f"{h}h{m:02d}m" if h else f"{m}m{s:02d}s"

    def tag(r):
        return (r["app_id"] if r.get("replica") is None
                else f"{r['app_id']}·{r['replica']}")

    width = max(6, max(len(tag(r)) for r in rows))
    print(f"{'APP-ID':<{width}}  {'PID':>7}  {'APP':>5}  {'SIDECAR':>7}  "
          f"{'HEALTH':<9}  {'COMPS':>5}  {'SUBS':>4}  {'ACTORS':>6}  UP")
    for r in rows:
        print(f"{tag(r):<{width}}  {r['pid'] or '-':>7}  "
              f"{r['app_port'] or '-':>5}  {r['sidecar_port'] or '-':>7}  "
              f"{r['health']:<9}  "
              f"{'-' if r['components'] is None else r['components']:>5}  "
              f"{'-' if r['subscriptions'] is None else r['subscriptions']:>4}  "
              f"{'-' if r.get('actors') is None else r['actors']:>6}  "
              f"{fmt_up(r['up_seconds'])}")
    if any_down:
        raise SystemExit(2)


def _cmd_actors(args) -> None:
    """The cluster's actor placement table, read from ``--app-id``'s
    sidecar (every replica computes the same table from the shared
    store). Default view aggregates per type: id count, owner replicas,
    lease age and fencing epoch ranges; ``--ids`` lists each actor id;
    ``--json`` dumps the raw document."""
    import json as json_mod

    addr, headers = _resolve_sidecar(args)

    async def go():
        import aiohttp

        url = f"{addr.base_url}/v1.0/actors"
        timeout = aiohttp.ClientTimeout(total=10.0)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            async with s.get(url, headers=headers) as r:
                return r.status, await r.read()

    status, raw = asyncio.run(go())
    if status == 404:
        raise SystemExit(
            "actor API not found — is TASKSRUNNER_ACTORS=1 set on the app?")
    if status >= 400:
        raise SystemExit(f"HTTP {status}: {raw.decode('utf-8', 'replace')}")
    doc = json_mod.loads(raw)
    if args.json:
        print(json_mod.dumps(doc, indent=2))
        return
    placement = doc.get("placement") or []
    if not placement:
        summary = doc.get("replica") or {}
        types = ", ".join(summary.get("types") or []) or "(none)"
        print(f"no actors placed yet (hosted types: {types})")
        return
    if args.ids:
        width = max(5, max(len(f"{r['type']}/{r['id']}") for r in placement))
        print(f"{'ACTOR':<{width}}  {'OWNER':<28}  {'EPOCH':>5}  "
              f"{'LEASE-AGE':>9}  ALIVE")
        for r in placement:
            print(f"{r['type'] + '/' + r['id']:<{width}}  "
                  f"{r.get('owner') or '-':<28}  {r.get('epoch') or 0:>5}  "
                  f"{r.get('lease_age', 0):>8.1f}s  "
                  f"{'yes' if r.get('alive') else 'NO'}")
        return
    by_type: dict[str, list[dict]] = {}
    for r in placement:
        by_type.setdefault(r["type"], []).append(r)
    width = max(4, max(len(t) for t in by_type))
    print(f"{'TYPE':<{width}}  {'IDS':>4}  {'OWNERS':>6}  {'EPOCH':>8}  "
          f"{'LEASE-AGE':>12}  DEAD")
    for atype, rows in sorted(by_type.items()):
        owners = {r.get("owner") for r in rows if r.get("owner")}
        epochs = [int(r.get("epoch") or 0) for r in rows]
        ages = [float(r.get("lease_age") or 0.0) for r in rows]
        dead = sum(1 for r in rows if not r.get("alive"))
        print(f"{atype:<{width}}  {len(rows):>4}  {len(owners):>6}  "
              f"{min(epochs)}-{max(epochs):<4}  "
              f"{min(ages):>5.1f}-{max(ages):<5.1f}  "
              f"{dead or '-'}")


def _cmd_workflows(args) -> None:
    """The workflow plane, via ``--app-id``'s sidecar: list instances,
    inspect one (``--history`` for the event log), start, terminate, or
    deliver an external event."""
    import json as json_mod

    addr, headers = _resolve_sidecar(args)
    input_doc = json_mod.loads(args.input) if args.input else None

    async def go():
        import aiohttp

        timeout = aiohttp.ClientTimeout(total=15.0)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            if args.start:
                url = (f"{addr.base_url}/v1.0/workflows/engine/"
                       f"{args.start}/start")
                params = ({"instanceID": args.instance}
                          if args.instance else None)
                async with s.post(url, headers=headers, json=input_doc,
                                  params=params) as r:
                    return r.status, await r.read()
            if args.terminate:
                url = (f"{addr.base_url}/v1.0/workflows/engine/"
                       f"{args.instance}/terminate")
                async with s.post(url, headers=headers,
                                  json={"reason": args.reason}) as r:
                    return r.status, await r.read()
            if args.raise_event:
                url = (f"{addr.base_url}/v1.0/workflows/engine/"
                       f"{args.instance}/raiseEvent/{args.raise_event}")
                async with s.post(url, headers=headers, json=input_doc) as r:
                    return r.status, await r.read()
            if args.instance:
                url = f"{addr.base_url}/v1.0/workflows/engine/{args.instance}"
                if args.history:
                    url += "/history"
                async with s.get(url, headers=headers) as r:
                    return r.status, await r.read()
            async with s.get(f"{addr.base_url}/v1.0/workflows",
                             headers=headers) as r:
                return r.status, await r.read()

    if (args.terminate or args.raise_event or args.history) \
            and not args.instance:
        raise SystemExit("this operation needs an instance id")
    status, raw = asyncio.run(go())
    if status == 404 and not args.instance and not args.start:
        raise SystemExit("workflow API not found — is "
                         "TASKSRUNNER_WORKFLOWS=1 set on the app?")
    if status >= 400:
        raise SystemExit(f"HTTP {status}: {raw.decode('utf-8', 'replace')}")
    if not raw:
        print("ok")
        return
    doc = json_mod.loads(raw)
    if args.json or args.history or args.start \
            or not isinstance(doc, dict) or "instances" not in doc:
        print(json_mod.dumps(doc, indent=2))
        return
    rows = doc["instances"]
    if not rows:
        print("no workflow instances")
        return
    width = max(8, max(len(r["instance"]) for r in rows))
    wfw = max(8, max(len(r.get("workflow") or "") for r in rows))
    print(f"{'INSTANCE':<{width}}  {'WORKFLOW':<{wfw}}  "
          f"{'STATUS':<10}  {'EVENTS':>6}  PARENT")
    for r in rows:
        print(f"{r['instance']:<{width}}  {r.get('workflow') or '-':<{wfw}}  "
              f"{r.get('status') or '-':<10}  {r.get('events') or 0:>6}  "
              f"{r.get('parent') or '-'}")


def _cmd_lint(args) -> None:
    from tasksrunner.analysis.engine import main as tasklint_main
    # argparse.REMAINDER keeps a leading "--" separator; drop it
    lint_args = [a for i, a in enumerate(args.lint_args)
                 if not (i == 0 and a == "--")]
    raise SystemExit(tasklint_main(lint_args))


def _cmd_verify(args) -> None:
    from tasksrunner.analysis.explore import KERNELS, verify
    kernels = None
    if args.kernel:
        unknown = [k for k in args.kernel if k not in KERNELS]
        if unknown:
            raise SystemExit(
                f"unknown kernel(s) {', '.join(unknown)}; "
                f"available: {', '.join(sorted(KERNELS))}")
        kernels = args.kernel
    raise SystemExit(verify(kernels))


def _cmd_components(args) -> None:
    from tasksrunner.component.loader import load_components
    from tasksrunner.component.registry import registered_types

    specs = load_components(args.path, app_id=args.app_id)
    known = set(registered_types())
    status_width = max((len(s.name) for s in specs), default=4)
    problems = 0
    for spec in specs:
        ok = spec.type in known
        if not ok:
            problems += 1
        scope = ",".join(spec.scopes) if spec.scopes else "(all apps)"
        print(f"{spec.name:<{status_width}}  {spec.type:<32} "
              f"{'ok' if ok else 'NO DRIVER':<10} scopes={scope}")
    if problems:
        raise SystemExit(f"{problems} component(s) have no registered driver")


def _resolve_sidecar(args):
    """Resolve ``--app-id``'s sidecar address + the auth headers every
    probe/flood command sends (one place for the token scheme)."""
    import os

    from tasksrunner.errors import AppNotFound
    from tasksrunner.invoke.resolver import NameResolver
    from tasksrunner.security import TOKEN_ENV, TOKEN_HEADER

    resolver = NameResolver(registry_file=args.registry_file)
    try:
        addr = resolver.resolve(args.app_id)
    except AppNotFound:
        known = ", ".join(resolver.known_apps()) or "(none registered)"
        raise SystemExit(
            f"app {args.app_id!r} is not registered; running apps: {known}")
    headers = {"Content-Type": "application/json"}
    token = os.environ.get(TOKEN_ENV)
    if token:
        headers[TOKEN_HEADER] = token
    return addr, headers


def _sidecar_request(args, method: str, path: str, body=None,
                     *, query: str = ""):
    """Shared plumbing for the probe commands: resolve ``--app-id``'s
    sidecar from the registry and issue one /v1.0 request against it —
    the same raw probes the workshop runs with curl at its manual
    verification checkpoints (docs/aca/04-aca-dapr-stateapi/
    index.md:41-75, docs/aca/05-aca-dapr-pubsubapi/index.md:60-88)."""
    import json as json_mod

    addr, base_headers = _resolve_sidecar(args)

    async def go():
        import aiohttp

        url = f"{addr.base_url}/v1.0/{path}"
        if query:
            url += "?" + query
        timeout = aiohttp.ClientTimeout(total=30.0)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            async with s.request(method, url, headers=base_headers,
                                 data=None if body is None
                                 else json_mod.dumps(body)) as r:
                raw = await r.read()
                return r.status, raw

    status, raw = asyncio.run(go())
    text = raw.decode("utf-8", "replace")
    try:
        parsed = json_mod.loads(text) if text else None
    except ValueError:
        parsed = None
    if parsed is not None:
        print(json_mod.dumps(parsed, indent=2))
    elif text:
        print(text)
    if status >= 400:
        raise SystemExit(f"HTTP {status}")
    return status


def _parse_data(raw: str | None):
    """--data accepts inline JSON or @file (curl convention)."""
    import json as json_mod

    if raw is None:
        return None
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            raw = f.read()
    try:
        return json_mod.loads(raw)
    except ValueError as exc:
        raise SystemExit(f"--data is not valid JSON: {exc}")


def _cmd_invoke(args) -> None:
    """≙ `dapr invoke` / the workshop's service-invocation probes
    (docs/aca/03-aca-dapr-integration/index.md:107-127): call
    /v1.0/invoke/{app-id}/method/{path} via the app's own sidecar."""
    method = args.verb.upper()
    path, _, query = args.method.partition("?")
    _sidecar_request(args, method, f"invoke/{args.app_id}/method/{path}",
                     _parse_data(args.data), query=query)


def _cmd_publish(args) -> None:
    """≙ `dapr publish`: POST /v1.0/publish/{pubsub}/{topic} through
    the sidecar of --app-id (scope decides which broker it sees).

    ``--count N`` floods N copies concurrently — the workshop's KEDA
    load test (Service Bus Explorer message floods + replica-list
    polling, docs/aca/09-aca-autoscale-keda/index.md:170-200) as one
    command; watch the scale-out with `tasksrunner ps`."""
    if args.count <= 1:
        _sidecar_request(args, "POST", f"publish/{args.pubsub}/{args.topic}",
                         _parse_data(args.data))
        return

    import time

    addr, headers = _resolve_sidecar(args)
    payload = _parse_data(args.data)

    async def flood():
        import aiohttp

        url = f"{addr.base_url}/v1.0/publish/{args.pubsub}/{args.topic}"
        sem = asyncio.Semaphore(32)
        failures = 0

        async def one(i):
            nonlocal failures
            async with sem:
                if isinstance(payload, dict):
                    body = dict(payload)
                    body.setdefault("floodSeq", i)
                elif payload is None:
                    body = {"floodSeq": i}
                else:
                    body = payload
                try:
                    async with session.post(url, json=body,
                                            headers=headers) as resp:
                        if resp.status >= 400:
                            failures += 1
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # any transport/timeout failure is one failed
                    # publish, never a crashed flood
                    failures += 1

        start = time.perf_counter()
        timeout = aiohttp.ClientTimeout(total=30.0)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            await asyncio.gather(*(one(i) for i in range(args.count)))
        elapsed = time.perf_counter() - start
        print(f"published {args.count - failures}/{args.count} to "
              f"{args.pubsub}/{args.topic} in {elapsed:.2f}s "
              f"({(args.count - failures) / max(elapsed, 1e-9):.0f}/s)"
              + (f", {failures} FAILED" if failures else ""))
        if failures:
            raise SystemExit(2)

    asyncio.run(flood())


def _cmd_state(args) -> None:
    """Raw state probes against a sidecar: the module-4 manual
    verification flow (POST /v1.0/state/{store}, GET by key) as a
    first-class command."""
    store = args.store
    if args.action == "get":
        if not args.key:
            raise SystemExit("state get needs a KEY")
        _sidecar_request(args, "GET", f"state/{store}/{args.key}")
    elif args.action == "set":
        if not args.key or args.data is None:
            raise SystemExit("state set needs a KEY and --data")
        _sidecar_request(args, "POST", f"state/{store}",
                         [{"key": args.key, "value": _parse_data(args.data)}])
    elif args.action == "delete":
        if not args.key:
            raise SystemExit("state delete needs a KEY")
        _sidecar_request(args, "DELETE", f"state/{store}/{args.key}")
    elif args.action == "query":
        _sidecar_request(args, "POST", f"state/{store}/query",
                         _parse_data(args.data) or {})


def _cmd_secret(args) -> None:
    """GET /v1.0/secrets/{store}/{key} (docs module 9 probe shape)."""
    _sidecar_request(args, "GET", f"secrets/{args.store}/{args.key}")


def _fetch_metadata(url: str, headers: dict, app_id: str) -> dict:
    import json as json_mod
    import urllib.error
    import urllib.request

    req = urllib.request.Request(url, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json_mod.loads(resp.read())
    except urllib.error.HTTPError as exc:
        hint = (" (set TASKSRUNNER_API_TOKEN — the sidecar requires it)"
                if exc.code == 401 else "")
        raise SystemExit(f"sidecar of {app_id!r} returned "
                         f"HTTP {exc.code}{hint}")
    except OSError as exc:
        raise SystemExit(f"cannot reach sidecar of {app_id!r}: {exc}")


def _fetch_all_replica_metadata(args) -> list[dict]:
    """Metadata from EVERY registered replica of the app — the
    percentile/exemplar views must merge the whole app, not sample
    whichever replica the round-robin resolver lands on."""
    import os

    from tasksrunner.errors import AppNotFound
    from tasksrunner.invoke.resolver import NameResolver
    from tasksrunner.security import TOKEN_ENV, TOKEN_HEADER

    resolver = NameResolver(registry_file=args.registry_file)
    try:
        addrs = resolver.resolve_all(args.app_id)
    except AppNotFound:
        addrs = []
    if not addrs:
        known = ", ".join(resolver.known_apps()) or "(none registered)"
        raise SystemExit(
            f"app {args.app_id!r} is not registered; running apps: {known}")
    headers = {}
    token = os.environ.get(TOKEN_ENV)
    if token:
        headers[TOKEN_HEADER] = token
    payloads = []
    for addr in addrs:
        try:
            payloads.append(_fetch_metadata(
                f"{addr.base_url}/v1.0/metadata", headers, args.app_id))
        except SystemExit:
            continue  # a dead replica must not fail the merged view
    if not payloads:
        raise SystemExit(f"no reachable replica of {args.app_id!r}")
    return payloads


def _metrics_percentiles(args) -> None:
    import json as json_mod

    from tasksrunner.observability.metrics import (
        merge_histogram_snapshots,
        summarize_histograms,
    )

    payloads = _fetch_all_replica_metadata(args)
    merged = merge_histogram_snapshots(
        p.get("histograms") or {} for p in payloads)
    rows = summarize_histograms(merged)
    if args.json:
        print(json_mod.dumps(
            {"replicas": len(payloads), "percentiles": rows}, indent=2))
        return
    if not rows:
        print(f"no latency histograms recorded for {args.app_id} "
              "(is TASKSRUNNER_HISTOGRAMS=0 set?)")
        return
    print(f"# merged across {len(payloads)} replica(s); values in ms")
    name_of = lambda r: (  # noqa: E731
        r["name"] + ("{" + ",".join(
            f"{k}={v}" for k, v in sorted(r["labels"].items())) + "}"
            if r["labels"] else ""))
    width = max(len(name_of(r)) for r in rows)
    print(f"{'series':<{width}}  {'count':>7}  {'p50':>8}  {'p95':>8}  {'p99':>8}")
    for r in rows:
        print(f"{name_of(r):<{width}}  {r['count']:>7}  "
              f"{r['p50'] * 1000:>8.2f}  {r['p95'] * 1000:>8.2f}  "
              f"{r['p99'] * 1000:>8.2f}")


def _metrics_slow(args) -> None:
    import json as json_mod

    from tasksrunner.observability.metrics import merge_histogram_snapshots

    payloads = _fetch_all_replica_metadata(args)
    merged = merge_histogram_snapshots(
        p.get("histograms") or {} for p in payloads)
    hits = []
    for name, hist in sorted(merged.items()):
        if args.slow not in name:
            continue
        for series in hist["series"]:
            for trace_id, value, when in series.get("exemplars", ()):
                hits.append({"name": name, "labels": series["labels"],
                             "trace_id": trace_id, "seconds": value,
                             "time": when})
    hits.sort(key=lambda h: h["seconds"], reverse=True)
    if args.json:
        print(json_mod.dumps(
            {"replicas": len(payloads), "slow": hits}, indent=2))
        return
    if not hits:
        print(f"no slow-call exemplars matching {args.slow!r} "
              "(observations must exceed TASKSRUNNER_SLOW_THRESHOLD_SECONDS, "
              "default 0.25, inside a trace)")
        return
    print(f"# slowest observations matching {args.slow!r} "
          f"across {len(payloads)} replica(s)")
    for h in hits:
        tag = ",".join(f"{k}={v}" for k, v in sorted(h["labels"].items()))
        print(f"{h['seconds'] * 1000:9.1f} ms  {h['name']}"
              f"{'{' + tag + '}' if tag else ''}  trace {h['trace_id']}")
    print(f"# drill down: tasksrunner traces show {hits[0]['trace_id']}")
    print("# blame chain: tasksrunner traces critical "
          f"{hits[0]['trace_id']}")


def _cmd_metrics(args) -> None:
    """An app's counters from its sidecar metadata (≙ the App
    Insights metrics view, SURVEY §5.5): invokes, state ops,
    publishes, deliveries — per label. ``--percentiles`` and
    ``--slow`` merge latency histograms/exemplars across every
    replica."""
    import json as json_mod

    args.app_id = args.app_id or args.app_id_pos
    if not args.app_id:
        raise SystemExit("metrics: an app id is required "
                         "(tasksrunner metrics <app-id>)")
    if getattr(args, "percentiles", False):
        _metrics_percentiles(args)
        return
    if getattr(args, "slow", None):
        _metrics_slow(args)
        return
    addr, headers = _resolve_sidecar(args)
    meta = _fetch_metadata(f"{addr.base_url}/v1.0/metadata", headers,
                           args.app_id)
    metrics = meta.get("metrics") or {}
    if args.json:
        print(json_mod.dumps(metrics, indent=2))
        return
    if not metrics:
        print(f"no metrics recorded for {args.app_id}")
        return
    width = max(len(k) for k in metrics)
    for key in sorted(metrics):
        value = metrics[key]
        shown = int(value) if float(value).is_integer() else round(value, 3)
        print(f"{key:<{width}}  {shown}")


def _cmd_chaos(args) -> None:
    """Admin surface for the fault-injection subsystem: show the gate,
    validate the Chaos documents in a resources dir (the same load-time
    validation a starting host runs), list every rule/target binding,
    and — when a running app is named — its live injection counters."""
    import json as json_mod

    from tasksrunner.chaos import ChaosPolicies, chaos_enabled, load_chaos

    specs = load_chaos(args.resources)  # raises on malformed docs
    policies = ChaosPolicies(specs, app_id=args.app_id)
    rules = policies.describe()
    enabled = chaos_enabled()

    live: dict[str, float] = {}
    if args.app_id:
        addr = None
        try:
            addr, headers = _resolve_sidecar(args)
        except SystemExit:
            pass  # not running — static view only
        if addr is not None:
            import urllib.error
            import urllib.request

            req = urllib.request.Request(f"{addr.base_url}/v1.0/metadata",
                                         headers=headers)
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    meta = json_mod.loads(resp.read())
                live = {
                    k: v for k, v in (meta.get("metrics") or {}).items()
                    if k.startswith(("chaos_injected_total",
                                     "resiliency_breaker_state",
                                     "resiliency_retry"))
                }
            except (urllib.error.URLError, OSError, ValueError):
                pass

    if args.json:
        print(json_mod.dumps(
            {"enabled": enabled, "documents": len(specs),
             "rules": rules, "metrics": live}, indent=2))
        if not enabled and specs:
            raise SystemExit(3)
        return

    print(f"chaos gate: {'ON (TASKSRUNNER_CHAOS=1)' if enabled else 'off'}")
    if not specs:
        print(f"no Chaos documents under {args.resources}")
        return
    print(f"{len(specs)} Chaos document(s), all valid")
    width = max(len(r["rule"]) for r in rules)
    for r in rules:
        params = ", ".join(f"{k}={v}" for k, v in r["params"].items()
                           if v not in (None, 0.0))
        state = " [disabled]" if r["disabled"] else ""
        print(f"  {r['rule']:<{width}}  {r['fault']}({params}){state}")
        for t in r["targets"]:
            print(f"  {'':<{width}}    -> {t}")
    if live:
        print("live counters:")
        lw = max(len(k) for k in live)
        for key in sorted(live):
            value = live[key]
            shown = int(value) if float(value).is_integer() else round(value, 3)
            print(f"  {key:<{lw}}  {shown}")
    if not enabled:
        # documents present but inert: the state an operator most
        # often means to ask about — make it unmissable and scriptable
        print("NOTE: documents are inert until the host runs with "
              "TASKSRUNNER_CHAOS=1")
        raise SystemExit(3)


def _cmd_repl(args) -> None:
    """Replication status straight from the on-disk databases — works
    with or without a live runtime (the sqlite files ARE the truth):
    the shared meta db holds each shard's leadership lease, and every
    member file's repl_meta row names its applied position."""
    import json as json_mod
    import pathlib
    import sqlite3
    import time as time_mod

    from tasksrunner.state.replication import (
        MAX_REPLICAS,
        _member_path,
        _meta_path,
    )

    base = args.database
    meta = _meta_path(base)
    if meta == ":memory:" or not pathlib.Path(meta).is_file():
        raise SystemExit(
            f"no replication meta database next to {base} (expected "
            f"{meta}) — is the store configured with replicas > 1?")
    con = sqlite3.connect(meta)
    try:
        leases = con.execute(
            "SELECT key, value FROM state WHERE key LIKE 'repl-lease||%'"
        ).fetchall()
    finally:
        con.close()
    if not leases:
        raise SystemExit(f"{meta} holds no shard leases yet — no leader "
                         "has started")
    now = time_mod.time()
    shard_count = 1 + max(int(key.split("||")[2]) for key, _ in leases)
    out = []
    for key, raw in sorted(leases):
        _, name, shard_str = key.split("||")
        shard = int(shard_str)
        rec = json_mod.loads(raw)
        members = []
        for m in range(MAX_REPLICAS):
            mpath = _member_path(base, shard, m, shard_count)
            if not pathlib.Path(mpath).is_file():
                continue
            mcon = sqlite3.connect(mpath)
            try:
                row = mcon.execute(
                    "SELECT hwm, epoch FROM repl_meta WHERE id = 1"
                ).fetchone()
            except sqlite3.OperationalError:
                row = None  # member file predates replication tables
            finally:
                mcon.close()
            if row is not None:
                members.append(
                    {"member": f"r{m}", "hwm": row[0], "epoch": row[1]})
        out.append({
            "store": name, "shard": shard,
            "leader": rec.get("owner"), "epoch": rec.get("epoch"),
            "pid": rec.get("pid"),
            "lease_seconds_left": round(rec.get("expires", 0.0) - now, 2),
            "members": members,
        })
    if args.json:
        print(json_mod.dumps({"replication": out}, indent=2))
        return
    for entry in out:
        left = entry["lease_seconds_left"]
        state = "EXPIRED" if left <= 0 else f"{left:.1f}s left"
        print(f"{entry['store']} shard {entry['shard']}: leader "
              f"{entry['leader']} (epoch {entry['epoch']}, pid "
              f"{entry['pid']}, lease {state})")
        for m in entry["members"]:
            print(f"  {m['member']}: hwm {m['hwm']} epoch {m['epoch']}")


def _cmd_shards(args) -> None:
    """Elastic-placement view from the orchestrator admin plane: per
    sharded store — routing epoch, shard→host assignment, hot/cold
    ranking, in-flight migration, and the control loop's rebalance
    plan. The live-cluster complement to `tasksrunner repl`, which
    reads the sqlite files."""
    import json as json_mod

    doc = _admin_request(args.registry_file, "GET", "/admin/placement")
    if args.json:
        print(json_mod.dumps(doc, indent=2))
        return
    apps = doc.get("apps") or {}
    if not apps:
        print("no running apps")
        return
    if not doc.get("reshard"):
        print("NOTE: TASKSRUNNER_RESHARD is off — this is a one-shot "
              "sweep, not a live control loop")
    shown = 0
    for app_id, snap in sorted(apps.items()):
        for store, entry in sorted((snap.get("stores") or {}).items()):
            shown += 1
            migration = entry.get("migration")
            status = (f", migrating ({migration.get('phase')})"
                      if isinstance(migration, dict) else "")
            print(f"{app_id}/{store}: epoch {entry.get('epoch')}, "
                  f"{entry.get('shards')} shards, "
                  f"{entry.get('replicas_reporting')} replica(s) "
                  f"reporting{status}")
            assignment = entry.get("assignment") or {}
            leaders = entry.get("leaders") or {}
            for row in entry.get("ranking") or []:
                shard = row.get("shard")
                host = (assignment.get(str(shard))
                        or leaders.get(str(shard)) or "local")
                heat = "HOT" if row.get("hot") else "ok"
                print(f"  shard {shard}: rank {row.get('rank')}, "
                      f"{row.get('rate')} ops/s [{heat}] @ {host}")
            plan = entry.get("plan")
            if plan:
                print(f"  plan: {plan.get('action')} shard "
                      f"{plan.get('shard')} — {plan.get('reason')}")
    if not shown:
        print("no sharded stores reporting placement (stores with "
              "shards > 1 publish it via sidecar metadata)")


def _admin_request(registry_file: str, method: str, path: str,
                   body: dict | None = None) -> dict:
    """Talk to the orchestrator's control plane (the `az containerapp`
    verbs surface). Its address comes from orchestrator.json next to
    the registry file."""
    import json as json_mod
    import os
    import urllib.error
    import urllib.request

    from tasksrunner.orchestrator.admin import info_path
    from tasksrunner.security import TOKEN_ENV, TOKEN_HEADER

    info_file = info_path(registry_file)
    if not info_file.is_file():
        raise SystemExit(
            f"no orchestrator control file at {info_file} — is "
            "`tasksrunner run` running with this registry_file?")
    try:
        info = json_mod.loads(info_file.read_text())
        url = info["admin_url"] + path
    except (ValueError, KeyError, TypeError):
        # a torn/garbage control file can only be crash debris (writes
        # are atomic rename); heal by removing it so the next
        # orchestrator start or CLI call sees a clean slate
        try:
            info_file.unlink()
        except OSError:
            pass
        raise SystemExit(
            f"orchestrator control file {info_file} was unreadable "
            "(crash debris?) — removed it; if `tasksrunner run` is "
            "live, retry in a moment, else restart it")
    headers = {"content-type": "application/json"}
    token = os.environ.get(TOKEN_ENV)
    if token:
        headers[TOKEN_HEADER] = token
    req = urllib.request.Request(
        url, method=method, headers=headers,
        data=json_mod.dumps(body).encode() if body is not None else None)
    # generous timeout: a rolling restart legitimately takes up to
    # ~40s per replica before the orchestrator responds
    timeout = 300
    timed_out = SystemExit(
        f"orchestrator did not answer within {timeout}s — the operation "
        "may still be running; check `tasksrunner ps` / `revisions`")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json_mod.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as exc:
        detail = exc.read().decode("utf-8", "replace")
        try:
            detail = json_mod.loads(detail).get("error", detail)
        except (ValueError, AttributeError):
            pass
        raise SystemExit(f"orchestrator returned {exc.code}: {detail}")
    except TimeoutError:
        raise timed_out
    except OSError as exc:
        # a connect-phase timeout arrives as URLError(socket.timeout),
        # an OSError — that's still "slow", not "unreachable", and the
        # stale-file hint would mislead during a long rolling restart
        if isinstance(getattr(exc, "reason", exc), TimeoutError):
            raise timed_out
        raise SystemExit(f"cannot reach orchestrator at {url}: {exc} "
                         "(stale orchestrator.json after a crash?)")


def _cmd_restart(args) -> None:
    """≙ `az containerapp revision restart`: rolling-restart an app's
    replicas through the orchestrator."""
    out = _admin_request(args.registry_file, "POST",
                        f"/admin/apps/{args.app_id}/restart")
    rev = out.get("revision", {})
    print(f"restarted {args.app_id} (revision {rev.get('revision')})")


def _cmd_logs(args) -> None:
    """≙ `az containerapp logs show --tail N`."""
    query = f"?tail={args.tail}"
    if args.replica is not None:
        query += f"&replica={args.replica}"
    out = _admin_request(args.registry_file, "GET",
                        f"/admin/apps/{args.app_id}/logs{query}")
    for entry in out.get("lines", []):
        print(f"[{args.app_id}·{entry['replica']}] {entry['line']}")


def _cmd_scale(args) -> None:
    """≙ `az containerapp update --min-replicas/--max-replicas`."""
    if args.min_replicas is None and args.max_replicas is None:
        raise SystemExit("nothing to do: pass --min-replicas and/or --max-replicas")
    body = {}
    if args.min_replicas is not None:
        body["min_replicas"] = args.min_replicas
    if args.max_replicas is not None:
        body["max_replicas"] = args.max_replicas
    out = _admin_request(args.registry_file, "POST",
                        f"/admin/apps/{args.app_id}/scale", body)
    rev = out.get("revision", {})
    print(f"scaled {args.app_id}: min={rev.get('min_replicas')} "
          f"max={rev.get('max_replicas')} (revision {rev.get('revision')})")


def _cmd_update(args) -> None:
    """≙ `az containerapp update --set-env-vars K=V --remove-env-vars K`:
    apply an env change as a new revision (rolling restart)."""
    set_env = {}
    for pair in args.set_env or []:
        if "=" not in pair:
            raise SystemExit(f"--set-env needs KEY=VALUE, got {pair!r}")
        key, _, value = pair.partition("=")
        set_env[key] = value
    remove = args.remove_env or []
    if not set_env and not remove:
        raise SystemExit("nothing to do: pass --set-env and/or --remove-env")
    out = _admin_request(args.registry_file, "POST",
                        f"/admin/apps/{args.app_id}/env",
                        {"set": set_env, "remove": remove})
    rev = out.get("revision", {})
    print(f"updated {args.app_id} env (revision {rev.get('revision')}): "
          f"set={sorted(set_env) or '-'} removed={remove or '-'}")


def _cmd_revisions(args) -> None:
    """≙ `az containerapp revision list`: the app's config-change
    history; the newest revision is the active one."""
    import time as time_mod

    out = _admin_request(args.registry_file, "GET",
                        f"/admin/apps/{args.app_id}/revisions")
    revisions = out.get("revisions", [])
    if not revisions:
        print(f"no revisions recorded for {args.app_id}")
        return
    print(f"{'REV':>4} {'CREATED':<20} {'ACTIVE':<7} REASON")
    for rev in revisions:
        created = time_mod.strftime("%Y-%m-%d %H:%M:%S",
                                    time_mod.localtime(rev["created"]))
        details = {k: v for k, v in rev.items()
                   if k not in ("revision", "created", "active", "reason")}
        suffix = f"  {details}" if details else ""
        print(f"{rev['revision']:>4} {created:<20} "
              f"{'yes' if rev['active'] else 'no':<7} {rev['reason']}{suffix}")


def _print_dlq(action: str, get_entries, ops: dict, where: str, ids) -> None:
    import json as json_mod

    if action == "list":
        entries = get_entries()
        if not entries:
            print(f"no dead letters on {where}")
            return
        print(f"{'ID':<36} {'ATTEMPTS':>8}  DATA")
        for e in entries:
            preview = json_mod.dumps(e["data"])
            if len(preview) > 60:
                preview = preview[:57] + "..."
            print(f"{e['id']:<36} {e['attempts']:>8}  {preview}")
    elif action == "show":
        print(json_mod.dumps(get_entries(), indent=2, default=str))
    else:  # requeue | purge
        n = ops[action](ids or None)
        verb = "requeued" if action == "requeue" else "purged"
        print(f"{verb} {n} message(s) on {where}")


def _cmd_dlq(args) -> None:
    """Dead-letter queue operations (≙ peeking/resubmitting a Service
    Bus subscription's DLQ or a Storage-queue poison queue; SURVEY
    §5.3's bounded-redelivery contract parks exhausted messages here).

    Pub/sub components take TOPIC (+ --group/--app-id); queue-binding
    components (bindings.azure.storagequeues etc.) take neither."""
    from tasksrunner.component.loader import load_components
    from tasksrunner.errors import ComponentError

    specs = load_components(args.resources)
    spec = next((s for s in specs if s.name == args.component), None)
    if spec is None:
        known = ", ".join(sorted(s.name for s in specs)) or "(none)"
        raise SystemExit(
            f"no component {args.component!r} in {args.resources}; found: {known}")

    if spec.type.startswith("bindings."):
        from tasksrunner.bindings.localqueue import open_queue_for_inspection
        try:
            queue = open_queue_for_inspection(spec, args.base_dir)
        except ComponentError as exc:
            raise SystemExit(str(exc))
        try:
            _print_dlq(args.action, queue.dead_letter_detail,
                       {"requeue": queue.requeue_dead_letters,
                        "purge": queue.purge_dead_letters},
                       args.component, args.id)
        finally:
            queue.close()
        return

    if not args.topic:
        raise SystemExit("pub/sub dlq needs a TOPIC")
    group = args.group or args.app_id
    if not group:
        raise SystemExit("pass --group (the consumer group; by convention "
                         "the subscriber's app-id)")
    from tasksrunner.pubsub.sqlite import open_for_inspection
    try:
        # base_dir anchors relative brokerPath the way the serving apps
        # do: against the run-config's directory
        broker = open_for_inspection(spec, args.base_dir)
    except ComponentError as exc:
        raise SystemExit(str(exc))
    try:
        _print_dlq(args.action,
                   lambda: broker.dead_letter_detail(args.topic, group),
                   {"requeue": lambda ids: broker.requeue_dead_letters(
                        args.topic, group, msg_ids=ids),
                    "purge": lambda ids: broker.purge_dead_letters(
                        args.topic, group, msg_ids=ids)},
                   f"{args.topic}/{group}", args.id)
    finally:
        broker.close_sync()


def _cmd_stop(args) -> None:
    """≙ `dapr stop --app-id X`: SIGTERM the registered host process."""
    import os
    import signal

    from tasksrunner.errors import AppNotFound
    from tasksrunner.invoke.resolver import NameResolver

    resolver = NameResolver(registry_file=args.registry_file)
    replicas = resolver.resolve_all(args.app_id)
    if not replicas:
        known = ", ".join(resolver.known_apps()) or "(none registered)"
        raise SystemExit(
            f"app {args.app_id!r} is not registered; running apps: {known}")
    # every replica of the app, as `dapr stop` stops the whole app —
    # each outcome reported on its own line, never summarized away
    signalled = 0
    failures = []
    for addr in replicas:
        if not addr.pid:
            failures.append(f"registry has no pid for {args.app_id!r}")
            continue
        try:
            os.kill(addr.pid, signal.SIGTERM)
        except ProcessLookupError:
            failures.append(
                f"{args.app_id}: pid {addr.pid} is already gone "
                f"(stale registration)")
        else:
            signalled += 1
            print(f"sent SIGTERM to {args.app_id} (pid {addr.pid})")
    for msg in failures:
        print(f"warning: {msg}", file=sys.stderr)
    if not signalled:
        raise SystemExit("; ".join(failures))


def _run_until_interrupt(coro) -> None:
    # every server entry point (host/serve/sidecar/run) funnels through
    # here, so the optional uvloop policy covers them all
    from tasksrunner.eventloop import maybe_enable_uvloop

    maybe_enable_uvloop()
    try:
        asyncio.run(coro)
    except KeyboardInterrupt:
        pass


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tasksrunner",
        description="Distributed-application runtime: building blocks, "
                    "sidecars, and a local multi-app orchestrator.",
    )
    parser.add_argument("--log-level", default="info",
                        choices=["debug", "info", "warning", "error"])
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("host", help="run one app + its sidecar in one process")
    p.add_argument("module", help="pkg.module:factory producing a tasksrunner.App")
    p.add_argument("--app-id", default=None,
                   help="override the App's app-id (rarely needed)")
    p.add_argument("--app-port", type=int, default=0)
    p.add_argument("--sidecar-port", type=int, default=0)
    p.add_argument("--host", default="127.0.0.1",
                   help="app server bind address (0.0.0.0 = external ingress)")
    p.add_argument("--components", default=None)
    p.add_argument("--registry-file", default=".tasksrunner/apps.json")
    p.add_argument("--no-register", action="store_true")
    p.set_defaults(fn=_cmd_host)

    p = sub.add_parser("serve", help="run an app server only (no sidecar)")
    p.add_argument("module")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--sidecar-port", type=int, default=None,
                   help="port of the sidecar this app's client talks to")
    p.set_defaults(fn=_cmd_serve)

    p = sub.add_parser("sidecar", help="run a sidecar for an app process")
    p.add_argument("--app-id", required=True)
    p.add_argument("--app-port", type=int, required=True)
    p.add_argument("--port", type=int, default=3500)
    p.add_argument("--components", default=None)
    p.add_argument("--registry-file", default=".tasksrunner/apps.json")
    p.set_defaults(fn=_cmd_sidecar)

    p = sub.add_parser("run", help="run a multi-app config (orchestrator)")
    p.add_argument("config")
    p.add_argument("--standby", action="store_true",
                   help="wait for the control-plane lease and take over "
                        "when the current orchestrator dies")
    p.add_argument("--no-adopt", action="store_true",
                   help="respawn replicas instead of re-adopting live "
                        "ones a previous orchestrator left running")
    p.set_defaults(fn=_cmd_run)

    p = sub.add_parser(
        "repl",
        help="replication status of a replicated store (leases, per-"
             "member positions) straight from its sqlite files")
    p.add_argument("database",
                   help="base sqlite path of the store (e.g. data/tasks.db)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p.set_defaults(fn=_cmd_repl)

    p = sub.add_parser(
        "shards",
        help="elastic-placement status of sharded stores (routing "
             "epoch, heat ranking, migrations, rebalance plan) from "
             "the orchestrator admin plane")
    p.add_argument("--registry-file", default=".tasksrunner/apps.json")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p.set_defaults(fn=_cmd_shards)

    p = sub.add_parser(
        "deploy",
        help="validate / what-if / apply / down an environment manifest")
    p.add_argument("action", choices=["validate", "what-if", "apply", "down"])
    p.add_argument("manifest")
    p.set_defaults(fn=_cmd_deploy)

    p = sub.add_parser(
        "traces",
        help="inspect recorded traces (transaction search, span tree, "
             "critical path, service map)")
    p.add_argument("action",
                   choices=["list", "show", "critical", "map", "query"])
    p.add_argument("trace_id", nargs="?", default=None,
                   help="trace id for `show`/`critical`; SQL for `query`")
    p.add_argument("--db", default=".tasksrunner/traces.db",
                   help="span DB path; comma-separate several to "
                        "assemble one trace across replicas")
    p.add_argument("--limit", type=int, default=20)
    p.add_argument("--mermaid", action="store_true",
                   help="emit the service map as a mermaid graph "
                        "(paste into any mkdocs/mermaid renderer)")
    p.set_defaults(fn=_cmd_traces)

    p = sub.add_parser(
        "flightrec",
        help="inspect black-box flight-recorder dumps")
    p.add_argument("--dir", default=".tasksrunner/flightrec",
                   help="dump directory (TASKSRUNNER_FLIGHTREC_DIR)")
    p.add_argument("--dump", default=None,
                   help="render one dump file instead of listing")
    p.add_argument("--limit", type=int, default=40,
                   help="ring entries shown from the end of a dump")
    p.set_defaults(fn=_cmd_flightrec)

    p = sub.add_parser(
        "ps", help="live status of registered apps (health, ports, components)")
    p.add_argument("--registry-file", default=".tasksrunner/apps.json")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p.set_defaults(fn=_cmd_ps)

    p = sub.add_parser(
        "lint",
        help="tasklint: AST checks for the runtime's concurrency, "
             "env-flag, metric-name, and error-taxonomy invariants")
    # everything after `lint` goes verbatim to the tasklint argparser
    # (python -m tasksrunner.analysis is the same entrypoint)
    p.add_argument("lint_args", nargs=argparse.REMAINDER, metavar="...",
                   help="tasklint arguments; try `tasksrunner lint -- --help`")
    p.set_defaults(fn=_cmd_lint)

    p = sub.add_parser(
        "verify",
        help="run the protocol kernels (lease takeover, quorum append, "
             "workflow turn commit) under exhaustive interleavings with "
             "crash points and check their invariants")
    p.add_argument("--kernel", action="append", metavar="NAME",
                   help="verify only this kernel (repeatable); default all")
    p.set_defaults(fn=_cmd_verify)

    p = sub.add_parser("components", help="validate a components directory")
    p.add_argument("path")
    p.add_argument("--app-id", default=None,
                   help="show only components in this app's scope")
    p.set_defaults(fn=_cmd_components)

    registry_arg = dict(default=".tasksrunner/apps.json",
                        help="name-registry file written by running hosts")

    p = sub.add_parser(
        "invoke", help="call a method on a running app via its sidecar")
    p.add_argument("app_id")
    p.add_argument("method", help='route, e.g. "api/tasks?createdBy=a@x.com"')
    p.add_argument("--verb", default="GET",
                   choices=["GET", "POST", "PUT", "DELETE", "PATCH",
                            "get", "post", "put", "delete", "patch"])
    p.add_argument("--data", default=None, help="JSON body or @file")
    p.add_argument("--registry-file", **registry_arg)
    p.set_defaults(fn=_cmd_invoke)

    p = sub.add_parser(
        "publish", help="publish an event through a running app's sidecar")
    p.add_argument("pubsub", help="pub/sub component name")
    p.add_argument("topic")
    p.add_argument("--app-id", required=True,
                   help="whose sidecar to publish through (decides scope)")
    p.add_argument("--data", default=None, help="JSON payload or @file")
    p.add_argument("--count", type=int, default=1,
                   help="flood N copies concurrently (KEDA load test)")
    p.add_argument("--registry-file", **registry_arg)
    p.set_defaults(fn=_cmd_publish)

    p = sub.add_parser(
        "state", help="raw state-store probes via a running app's sidecar")
    p.add_argument("action", choices=["get", "set", "delete", "query"])
    p.add_argument("store", help="state component name, e.g. statestore")
    p.add_argument("key", nargs="?", default=None)
    p.add_argument("--app-id", required=True)
    p.add_argument("--data", default=None,
                   help="JSON value (set) or query document (query)")
    p.add_argument("--registry-file", **registry_arg)
    p.set_defaults(fn=_cmd_state)

    p = sub.add_parser(
        "secret", help="read a secret via a running app's sidecar")
    p.add_argument("store")
    p.add_argument("key")
    p.add_argument("--app-id", required=True)
    p.add_argument("--registry-file", **registry_arg)
    p.set_defaults(fn=_cmd_secret)

    p = sub.add_parser(
        "actors", help="the virtual-actor placement table "
                       "(type → ids → owner → lease/epoch)")
    p.add_argument("--app-id", required=True,
                   help="any actor-hosting app; every replica serves the "
                        "same table")
    p.add_argument("--ids", action="store_true",
                   help="one row per actor id instead of the per-type "
                        "aggregate")
    p.add_argument("--json", action="store_true")
    p.add_argument("--registry-file", **registry_arg)
    p.set_defaults(fn=_cmd_actors)

    p = sub.add_parser(
        "workflows", help="durable workflow instances "
                          "(list / status / start / terminate / raise)")
    p.add_argument("instance", nargs="?", default=None,
                   help="instance id: show its status (default lists all)")
    p.add_argument("--app-id", required=True,
                   help="any workflow-hosting app replica")
    p.add_argument("--history", action="store_true",
                   help="dump the instance's full event history")
    p.add_argument("--start", default=None, metavar="WORKFLOW",
                   help="start WORKFLOW (optionally with a fixed instance "
                        "id and --input)")
    p.add_argument("--terminate", action="store_true",
                   help="terminate the instance (--reason records why)")
    p.add_argument("--reason", default="terminated")
    p.add_argument("--raise-event", default=None, metavar="EVENT",
                   help="deliver external event EVENT (payload via --input)")
    p.add_argument("--input", default=None,
                   help="JSON payload for --start / --raise-event")
    p.add_argument("--json", action="store_true")
    p.add_argument("--registry-file", **registry_arg)
    p.set_defaults(fn=_cmd_workflows)

    p = sub.add_parser("stop", help="SIGTERM a registered app host")
    p.add_argument("app_id")
    p.add_argument("--registry-file", **registry_arg)
    p.set_defaults(fn=_cmd_stop)

    p = sub.add_parser("metrics",
                       help="an app's request/publish/delivery counters "
                            "(App Insights metrics view analog)")
    # positional like logs/stop/restart; --app-id kept for compatibility
    p.add_argument("app_id_pos", nargs="?", default=None, metavar="app_id")
    p.add_argument("--app-id", dest="app_id", default=None)
    p.add_argument("--json", action="store_true")
    p.add_argument("--percentiles", action="store_true",
                   help="p50/p95/p99 latency per histogram series, merged "
                        "across every replica of the app")
    p.add_argument("--slow", default=None, metavar="NAME",
                   help="trace exemplars behind the latency tail: slow "
                        "observations of histograms matching NAME, with "
                        "trace ids for `tasksrunner traces show`")
    p.add_argument("--registry-file", **registry_arg)
    p.set_defaults(fn=_cmd_metrics)

    p = sub.add_parser("dlq",
                       help="inspect / requeue a pubsub consumer group's "
                            "dead letters (Service Bus DLQ analog)")
    p.add_argument("action", choices=["list", "show", "requeue", "purge"])
    p.add_argument("component", help="pubsub or queue-binding component name")
    p.add_argument("topic", nargs="?", default=None,
                   help="topic (pub/sub components only)")
    p.add_argument("--group", default=None,
                   help="consumer group (defaults to --app-id)")
    p.add_argument("--app-id", default=None)
    p.add_argument("--id", action="append",
                   help="requeue only these message ids (repeatable)")
    p.add_argument("--resources", default="components",
                   help="components directory holding the pubsub YAML")
    p.add_argument("--base-dir", default=".",
                   help="directory relative brokerPath resolves against "
                        "(the run-config's directory)")
    p.set_defaults(fn=_cmd_dlq)

    p = sub.add_parser("chaos",
                       help="fault-injection status: gate, validated "
                            "rules/targets, live injection counters")
    p.add_argument("action", choices=["status"])
    p.add_argument("--resources", default="components",
                   help="resources directory holding the Chaos YAML")
    p.add_argument("--app-id", default=None,
                   help="scope the view to one app and fetch its live "
                        "counters when it is running")
    p.add_argument("--json", action="store_true")
    p.add_argument("--registry-file", **registry_arg)
    p.set_defaults(fn=_cmd_chaos)

    p = sub.add_parser("restart",
                       help="rolling-restart an app via the orchestrator "
                            "(≙ az containerapp revision restart)")
    p.add_argument("app_id")
    p.add_argument("--registry-file", **registry_arg)
    p.set_defaults(fn=_cmd_restart)

    p = sub.add_parser("logs",
                       help="recent output of an app's replicas "
                            "(≙ az containerapp logs show)")
    p.add_argument("app_id")
    p.add_argument("--tail", type=int, default=100)
    p.add_argument("--replica", type=int, default=None)
    p.add_argument("--registry-file", **registry_arg)
    p.set_defaults(fn=_cmd_logs)

    p = sub.add_parser("scale",
                       help="change an app's replica bounds "
                            "(≙ az containerapp update --min/--max-replicas)")
    p.add_argument("app_id")
    p.add_argument("--min-replicas", type=int, default=None)
    p.add_argument("--max-replicas", type=int, default=None)
    p.add_argument("--registry-file", **registry_arg)
    p.set_defaults(fn=_cmd_scale)

    p = sub.add_parser("update",
                       help="apply an env change as a new revision "
                            "(≙ az containerapp update --set-env-vars)")
    p.add_argument("app_id")
    p.add_argument("--set-env", action="append", metavar="KEY=VALUE")
    p.add_argument("--remove-env", action="append", metavar="KEY")
    p.add_argument("--registry-file", **registry_arg)
    p.set_defaults(fn=_cmd_update)

    p = sub.add_parser("revisions",
                       help="an app's config-change history "
                            "(≙ az containerapp revision list)")
    p.add_argument("app_id")
    p.add_argument("--registry-file", **registry_arg)
    p.set_defaults(fn=_cmd_revisions)

    return parser


def main(argv: list[str] | None = None) -> None:
    args = build_parser().parse_args(argv)
    from tasksrunner.errors import TasksRunnerError
    try:
        args.fn(args)
    except TasksRunnerError as exc:
        # user-facing errors (bad manifest path, unresolved secret...)
        # exit cleanly instead of dumping a traceback
        raise SystemExit(f"ERROR: {exc}") from exc
    except BrokenPipeError:
        # stdout consumer went away (e.g. `tasksrunner ps | head`)
        import os
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        raise SystemExit(0) from None


if __name__ == "__main__":
    main()
