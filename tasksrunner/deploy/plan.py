"""what-if / apply: plan-diff deployment against recorded state.

≙ the reference pipeline's ``az deployment group what-if`` preview and
deploy steps (.github/workflows/infra-deploy.yml:80-160): the applied
environment state is recorded (``.tasksrunner/deployed.json`` ≙ the
resource group's current state), ``what_if`` diffs desired vs recorded
without touching anything, ``apply`` records the new state and
materialises the runnable artifacts (a run config for the orchestrator
+ provisioned resource paths + resolved app secrets).
"""

from __future__ import annotations

import json
import os
import pathlib
from typing import Any

import yaml

from tasksrunner.deploy.manifest import (
    EnvironmentManifest,
    desired_state,
    validate_manifest,
)
from tasksrunner.errors import ComponentError

DEPLOYED_STATE = "deployed.json"


def _state_path(manifest: EnvironmentManifest) -> pathlib.Path:
    return manifest.base_dir / ".tasksrunner" / DEPLOYED_STATE


def _load_recorded(manifest: EnvironmentManifest) -> dict | None:
    path = _state_path(manifest)
    if not path.is_file():
        return None
    try:
        return json.loads(path.read_text())
    except ValueError:
        return None


def diff_states(recorded: Any, desired: Any, *, path: str = "") -> list[dict]:
    """Structural diff: list of {op: create|delete|modify, path, ...}."""
    changes: list[dict] = []
    if isinstance(recorded, dict) and isinstance(desired, dict):
        for key in sorted(set(recorded) | set(desired)):
            sub = f"{path}.{key}" if path else str(key)
            if key not in recorded:
                changes.append({"op": "create", "path": sub, "value": desired[key]})
            elif key not in desired:
                changes.append({"op": "delete", "path": sub, "value": recorded[key]})
            else:
                changes.extend(diff_states(recorded[key], desired[key], path=sub))
        return changes
    if recorded != desired:
        changes.append({"op": "modify", "path": path,
                        "from": recorded, "to": desired})
    return changes


def what_if(manifest: EnvironmentManifest) -> dict:
    """Preview: validate + diff desired vs recorded, touch nothing."""
    problems = validate_manifest(manifest)
    desired = desired_state(manifest) if not problems else {}
    recorded = _load_recorded(manifest)
    changes = (
        [{"op": "create", "path": "", "value": "(entire environment)"}]
        if recorded is None and not problems
        else diff_states(recorded or {}, desired)
    )
    return {
        "valid": not problems,
        "problems": problems,
        "first_deploy": recorded is None,
        "changes": changes,
    }


def _resolve_secret(name: str, spec: object, *, app_id: str) -> str:
    """Secret blocks: literal string, or {env: VAR} indirection (≙ the
    Key Vault reference / listKeys() indirections in the Bicep app
    modules, processor-backend-service.bicep:121-130)."""
    if isinstance(spec, str):
        return spec
    if isinstance(spec, dict) and "env" in spec:
        var = str(spec["env"])
        if var in os.environ:
            return os.environ[var]
        if "default" in spec:
            # ≙ the reference's `'dummy'` fallback for the sendgrid key
            # (secrets/processor-backend-service-secrets.bicep:36)
            return str(spec["default"])
        raise ComponentError(
            f"app {app_id!r}: secret {name!r} references unset env var {var!r}")
    raise ComponentError(f"app {app_id!r}: secret {name!r} must be a string or {{env: VAR}}")


def apply_manifest(manifest: EnvironmentManifest) -> dict:
    """Deploy: validate, record state, emit the orchestrator run config.

    Returns {"run_config": path, "state": path, "changes": [...]}.
    Secrets resolve at apply time into per-app env (the way a container
    app's secretRef env vars materialise at deploy), so the emitted run
    config is self-contained.
    """
    preview = what_if(manifest)
    if not preview["valid"]:
        raise ComponentError(
            "manifest is invalid:\n  - " + "\n  - ".join(preview["problems"]))
    if manifest.require_api_token:
        from tasksrunner.security import TOKEN_ENV
        if not os.environ.get(TOKEN_ENV):
            raise ComponentError(
                f"manifest requires an API token but {TOKEN_ENV} is not set "
                "in the deploying environment (the secure-baseline posture: "
                "no unauthenticated sidecar/control-plane access)")

    out_dir = manifest.base_dir / ".tasksrunner"
    out_dir.mkdir(parents=True, exist_ok=True)

    # materialise the run config the orchestrator consumes
    apps_block = []
    for app in manifest.apps:
        env = dict(app.env)
        for secret_name, spec in app.secrets.items():
            env_key = secret_name.replace("-", "_").upper()
            env[env_key] = _resolve_secret(secret_name, spec, app_id=app.app_id)
        entry: dict[str, Any] = {
            "app_id": app.app_id,
            "module": app.module,
            "app_port": app.app_port,
            "sidecar_port": app.sidecar_port,
            # ingress → bind address (external = reachable off-host,
            # ≙ the ACA external/internal ingress flag)
            "host": "0.0.0.0" if app.ingress == "external" else "127.0.0.1",
            "env": env,
        }
        if app.max_replicas > 1 or app.scale_rules:
            entry["scale"] = {
                "min_replicas": app.min_replicas,
                "max_replicas": app.max_replicas,
                "cooldown_seconds": app.cooldown_seconds,
                "rules": app.scale_rules,
            }
        if app.health is not None:
            entry["health"] = app.health
        if app.grants is not None:
            # least-privilege grants travel with the artifact (validated
            # at load; ≙ role assignments deployed with the app's Bicep)
            entry["grants"] = app.grants
        apps_block.append(entry)

    # components land in a generated resources dir, one local-dialect
    # file per component, names taken from the manifest
    from tasksrunner.component.loader import dump_components
    from tasksrunner.deploy.manifest import resolve_components

    resources_dir = out_dir / f"{manifest.name}-components"
    resources_dir.mkdir(parents=True, exist_ok=True)
    for old in resources_dir.glob("*.yaml"):
        old.unlink()
    specs = resolve_components(manifest)
    for spec in specs:
        (resources_dir / f"{spec.name}.yaml").write_text(dump_components([spec]))

    # anchor the registry at the manifest's own directory: the emitted
    # run config lives under .tasksrunner/, and a relative registry
    # path would otherwise nest a second .tasksrunner/ inside it
    registry = pathlib.Path(manifest.registry_file)
    if not registry.is_absolute():
        registry = manifest.base_dir / registry
    run_config = {
        "resources_path": str(resources_dir),
        "registry_file": str(registry),
        # replicas run with cwd = base_dir, so relative component paths
        # (.tasksrunner/statestore.db, the default broker/trace dbs)
        # resolve against the MANIFEST's directory. Without this the
        # orchestrator would anchor at the emitted config's parent —
        # .tasksrunner/ itself — and nest a second .tasksrunner/ inside
        "base_dir": str(manifest.base_dir),
        "apps": apps_block,
    }
    if manifest.require_api_token:
        # the posture travels with the artifact: the orchestrator will
        # refuse to start this config unauthenticated even from a
        # fresh shell (deploy-time check alone would not survive CI)
        run_config["require_api_token"] = True
    if manifest.per_app_tokens:
        run_config["per_app_tokens"] = True
    if manifest.mesh_tls:
        run_config["mesh_tls"] = True
    run_path = out_dir / f"{manifest.name}-run.yaml"
    run_path.write_text(yaml.safe_dump(run_config, sort_keys=False))

    state_path = _state_path(manifest)
    state_path.write_text(json.dumps(desired_state(manifest), indent=2))

    return {
        "run_config": str(run_path),
        "state": str(state_path),
        "changes": preview["changes"],
        "first_deploy": preview["first_deploy"],
    }


def destroy(manifest: EnvironmentManifest) -> bool:
    """Tear down the recorded environment (≙ the pipeline's manual
    teardown input, infra-deploy.yml:10-15). Returns True if state
    existed."""
    state = _state_path(manifest)
    existed = state.is_file()
    if existed:
        state.unlink()
    run_path = manifest.base_dir / ".tasksrunner" / f"{manifest.name}-run.yaml"
    if run_path.is_file():
        run_path.unlink()
    return existed
