from tasksrunner.deploy.manifest import (
    AppManifest,
    EnvironmentManifest,
    load_manifest,
    validate_manifest,
)
from tasksrunner.deploy.plan import apply_manifest, what_if

__all__ = [
    "AppManifest",
    "EnvironmentManifest",
    "load_manifest",
    "validate_manifest",
    "what_if",
    "apply_manifest",
]
