"""Declarative environment manifest — the framework's IaC layer.

Plays the role bicep/main.bicep plays in the reference: one composition
root declaring the environment, every component (cloud-dialect files,
named here the way ``az containerapp env dapr-component set`` names
them), and every app with its ingress/dapr/env/secrets/scale blocks
(bicep/modules/container-apps/webapi-backend-service.bicep:94-139,
processor-backend-service.bicep:113-181).

Shape:

```yaml
environment:
  name: tasks-tracker-env
  registry_file: .tasksrunner/apps.json
components:
  - name: statestore
    file: aca-components/containerapps-statestore.yaml
apps:
  - app_id: tasksmanager-backend-api
    module: samples.tasks_tracker.backend_api:make_app
    app_port: 5103
    sidecar_port: 3500
    ingress: internal          # external | internal | none
    env: { TASKS_MANAGER: store }
    secrets:                   # name -> value | {env: VAR}
      appinsights-key: { env: APPINSIGHTS_KEY }
    scale:
      min_replicas: 1
      max_replicas: 5
      rules: [ ... ]           # same schema as the run config
```

The verbs mirror the reference's CI pipeline
(.github/workflows/infra-deploy.yml:33-160): ``validate`` ≙ bicep lint
+ ARM Validate, ``what-if`` ≙ the az what-if diff preview, ``apply`` ≙
the deployment step.
"""

from __future__ import annotations

import importlib
import pathlib
from dataclasses import asdict, dataclass, field

import yaml

from tasksrunner.component.loader import load_component_file
from tasksrunner.component.registry import registered_types
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import ComponentError

INGRESS_MODES = ("external", "internal", "none")


@dataclass
class ComponentRef:
    name: str
    file: str


@dataclass
class AppManifest:
    app_id: str
    module: str
    app_port: int = 0
    sidecar_port: int = 0
    ingress: str = "internal"
    env: dict[str, str] = field(default_factory=dict)
    #: secret name -> literal value or {"env": "VAR_NAME"} indirection
    secrets: dict[str, object] = field(default_factory=dict)
    min_replicas: int = 1
    max_replicas: int = 1
    scale_rules: list[dict] = field(default_factory=list)
    cooldown_seconds: float = 5.0
    #: liveness-probe block passed through to the run config
    #: (≙ the ACA container probes section); None = defaults,
    #: False = probing off
    health: object = None
    #: per-app component grants (≙ the per-app role assignments the
    #: reference declares in Bicep, webapi-backend-service.bicep:146-165);
    #: None = unrestricted
    grants: dict | None = None


@dataclass
class EnvironmentManifest:
    name: str
    apps: list[AppManifest]
    components: list[ComponentRef] = field(default_factory=list)
    registry_file: str = ".tasksrunner/apps.json"
    #: when true, `apply` refuses to emit a run config unless the
    #: sidecar/control-plane API token is configured in the deploying
    #: environment — the secure-baseline posture (≙ the landing zone's
    #: "no unauthenticated data plane" rule)
    require_api_token: bool = False
    #: one generated token per app at run time (≙ one managed identity
    #: per container app); travels into the emitted run config
    per_app_tokens: bool = False
    #: mutual TLS on the sidecar mesh (≙ "Dapr sidecars communicate
    #: over mutual TLS"): environment CA + per-app workload certs
    mesh_tls: bool = False
    source_path: pathlib.Path | None = None

    @property
    def base_dir(self) -> pathlib.Path:
        return self.source_path.parent if self.source_path else pathlib.Path.cwd()


def load_manifest(path: str | pathlib.Path) -> EnvironmentManifest:
    path = pathlib.Path(path)
    try:
        doc = yaml.safe_load(path.read_text()) or {}
    except OSError as exc:
        raise ComponentError(f"cannot read manifest {path}: {exc}") from exc
    except yaml.YAMLError as exc:
        raise ComponentError(f"cannot parse manifest {path}: {exc}") from exc

    env = doc.get("environment") or {}
    apps = []
    for raw in doc.get("apps") or []:
        if "app_id" not in raw or "module" not in raw:
            raise ComponentError(f"{path}: each app needs app_id and module")
        scale = raw.get("scale") or {}
        apps.append(AppManifest(
            app_id=str(raw["app_id"]),
            module=str(raw["module"]),
            app_port=int(raw.get("app_port", 0)),
            sidecar_port=int(raw.get("sidecar_port", 0)),
            ingress=str(raw.get("ingress", "internal")),
            env={str(k): str(v) for k, v in (raw.get("env") or {}).items()},
            secrets=dict(raw.get("secrets") or {}),
            min_replicas=int(scale.get("min_replicas", 1)),
            max_replicas=int(scale.get("max_replicas", 1)),
            scale_rules=list(scale.get("rules") or []),
            cooldown_seconds=float(scale.get("cooldown_seconds", 5.0)),
            health=raw.get("health"),
            grants=raw.get("grants"),
        ))

    components = [
        ComponentRef(name=str(c["name"]), file=str(c["file"]))
        for c in doc.get("components") or []
        if isinstance(c, dict) and "name" in c and "file" in c
    ]

    return EnvironmentManifest(
        name=str(env.get("name", path.stem)),
        apps=apps,
        components=components,
        registry_file=str(env.get("registry_file", ".tasksrunner/apps.json")),
        require_api_token=bool(env.get("require_api_token", False)),
        per_app_tokens=bool(env.get("per_app_tokens", False)),
        mesh_tls=bool(env.get("mesh_tls", False)),
        source_path=path.resolve(),
    )


def resolve_components(manifest: EnvironmentManifest) -> list[ComponentSpec]:
    """Load every referenced component file with its manifest name
    (exactly how `az containerapp env dapr-component set --yaml` pairs
    a name with a cloud-dialect file)."""
    specs: list[ComponentSpec] = []
    for ref in manifest.components:
        file_path = pathlib.Path(ref.file)
        if not file_path.is_absolute():
            file_path = manifest.base_dir / file_path
        loaded = load_component_file(file_path, name=ref.name)
        if len(loaded) != 1:
            raise ComponentError(
                f"component file {file_path} must hold exactly one document")
        specs.append(loaded[0])
    return specs


def validate_manifest(manifest: EnvironmentManifest, *,
                      check_imports: bool = True) -> list[str]:
    """≙ lint + Validate deployment mode: return a list of problems
    (empty = valid)."""
    problems: list[str] = []
    if not manifest.apps:
        problems.append("manifest declares no apps")

    seen_ids: set[str] = set()
    seen_ports: dict[int, str] = {}
    for app in manifest.apps:
        where = f"app {app.app_id!r}"
        if app.app_id in seen_ids:
            problems.append(f"duplicate app_id {app.app_id!r}")
        seen_ids.add(app.app_id)
        if app.ingress not in INGRESS_MODES:
            problems.append(f"{where}: ingress must be one of {INGRESS_MODES}")
        if app.min_replicas < 1:
            problems.append(f"{where}: min_replicas must be >= 1 "
                            "(scale-to-zero starves cron/input bindings)")
        if app.max_replicas < app.min_replicas:
            problems.append(f"{where}: max_replicas < min_replicas")
        if app.health is not None:
            from tasksrunner.orchestrator.config import parse_health
            try:
                parse_health(app.health)
            except ComponentError as exc:
                problems.append(f"{where}: {exc}")
        for port in (app.app_port, app.sidecar_port):
            if port:
                if port in seen_ports:
                    problems.append(
                        f"{where}: port {port} already used by {seen_ports[port]}")
                seen_ports[port] = app.app_id
        if check_imports:
            module_name = app.module.partition(":")[0]
            try:
                importlib.import_module(module_name)
            except ImportError as exc:
                problems.append(f"{where}: module {module_name!r} not importable: {exc}")

    try:
        specs = resolve_components(manifest)
    except ComponentError as exc:
        problems.append(str(exc))
        specs = []

    known = set(registered_types())
    comp_names = set()
    for spec in specs:
        comp_names.add(spec.name)
        if spec.type not in known:
            problems.append(f"component {spec.name!r}: no driver for type {spec.type!r}")
        for scope in spec.scopes:
            if scope not in seen_ids:
                problems.append(
                    f"component {spec.name!r}: scope {scope!r} matches no app")

    for app in manifest.apps:
        for rule in app.scale_rules:
            comp = (rule.get("metadata") or {}).get("component")
            if comp and comp not in comp_names:
                problems.append(
                    f"app {app.app_id!r}: scale rule references unknown "
                    f"component {comp!r}")
        if app.grants is not None:
            from tasksrunner.security import AppGrants
            try:
                parsed = AppGrants.parse(app.grants, app_id=app.app_id)
            except ComponentError as exc:
                problems.append(str(exc))
            else:
                for comp in parsed.components:
                    if comp not in comp_names:
                        problems.append(
                            f"app {app.app_id!r}: grant references unknown "
                            f"component {comp!r}")
    return problems


def desired_state(manifest: EnvironmentManifest) -> dict:
    """Canonical JSON form of the manifest (the what-if diff input)."""
    specs = resolve_components(manifest)
    return {
        "environment": manifest.name,
        "components": {
            s.name: {
                "type": s.type,
                "version": s.version,
                "metadata": {
                    k: (v if isinstance(v, str) else
                        {"secretRef": v.key, "store": v.store})
                    for k, v in s.metadata.items()
                },
                "scopes": sorted(s.scopes),
            }
            for s in specs
        },
        "apps": {
            a.app_id: {
                k: v for k, v in asdict(a).items() if k != "app_id"
            }
            for a in manifest.apps
        },
    }
