"""Shared security constants.

App↔sidecar API-token auth ≙ Dapr's ``dapr-api-token`` / the
reference's identity posture (SURVEY.md §5.10). One definition so the
sidecar (verifier), the client SDK, and peer-sidecar invocation (both
senders) can never drift apart.
"""

TOKEN_ENV = "TASKSRUNNER_API_TOKEN"
TOKEN_HEADER = "tr-api-token"
