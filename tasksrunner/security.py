"""Security: app↔sidecar auth tokens and per-app component grants.

Two layers, mirroring the reference's identity posture (SURVEY.md
§5.10):

* **AuthN — API tokens** ≙ Dapr's ``dapr-api-token``. One definition of
  header/env names so the sidecar (verifier), the client SDK, and
  peer-sidecar invocation (both senders) can never drift apart. With
  ``per_app_tokens`` each app gets its OWN token (≙ one managed
  identity per container app, webapi-backend-service.bicep:83-86): an
  app can drive only its own sidecar; peer sidecars accept any cluster
  app's token for inbound service invocation — and nothing else.

* **AuthZ — grants** ≙ the reference's least-privilege role
  assignments: Cosmos "Data Contributor" (state read+write,
  webapi-backend-service.bicep:146-154), Service Bus "Data Sender"
  (publish, :157-165), "Data Receiver" (subscribe,
  processor-backend-service.bicep:190-198), Key Vault "Secrets User"
  (secret read, secrets/processor-backend-service-secrets.bicep:66-74).
  Declared per app in the run config / environment manifest:

  .. code-block:: yaml

      apps:
        - app_id: tasksmanager-backend-api
          grants:
            statestore: [read, write]
            dapr-pubsub-servicebus:
              - publish: [tasksavedtopic]    # entity-scoped send
            secretstoreakv: [read]

  An app WITHOUT a ``grants`` block is unrestricted (the pre-grants
  posture, like the workshop before module 10 introduces identities);
  an app WITH one may only perform the listed operations.

Operations per building block:

=============  =============================================
state          ``read`` (get/bulk/query), ``write`` (save/delete/transaction)
pubsub         ``publish``, ``subscribe`` — optionally per-topic
bindings       ``invoke`` (output bindings)
secretstores   ``read``
=============  =============================================
"""

from __future__ import annotations

import json
import os
import pathlib
from dataclasses import dataclass, field

from tasksrunner.errors import ComponentError, PermissionDenied

TOKEN_ENV = "TASKSRUNNER_API_TOKEN"
TOKEN_HEADER = "tr-api-token"
#: JSON file mapping app_id -> token; set for every replica when the
#: orchestrator runs with ``per_app_tokens: true``
TOKENS_FILE_ENV = "TASKSRUNNER_TOKENS_FILE"
#: per-app grants for the hosted app, JSON-encoded (orchestrator →
#: ``tasksrunner host`` hand-off)
GRANTS_ENV = "TASKSRUNNER_GRANTS"

_KNOWN_OPS = {"read", "write", "publish", "subscribe", "invoke"}


@dataclass
class AppGrants:
    """Per-app component permissions.

    ``components`` maps component name → {op → topic-allowlist or None}.
    A ``None`` allowlist means the op is granted for every topic (ops
    other than publish/subscribe ignore topics entirely).
    """

    components: dict[str, dict[str, list[str] | None]] = field(
        default_factory=dict)

    @classmethod
    def parse(cls, raw: object, *, app_id: str = "?") -> "AppGrants":
        """Parse the YAML/JSON ``grants:`` block. Accepts, per
        component, a list whose items are either an op string or a
        single-key ``{op: [topics]}`` mapping."""
        if raw is None:
            raw = {}
        if not isinstance(raw, dict):
            raise ComponentError(
                f"grants for app {app_id!r} must be a mapping of "
                f"component name to operation list, got {type(raw).__name__}")
        components: dict[str, dict[str, list[str] | None]] = {}
        for comp, ops_raw in raw.items():
            if ops_raw is None:
                ops_raw = []
            if isinstance(ops_raw, str):
                ops_raw = [ops_raw]
            if not isinstance(ops_raw, list):
                raise ComponentError(
                    f"grants[{comp}] for app {app_id!r} must be a list "
                    f"of operations")
            ops: dict[str, list[str] | None] = {}
            for entry in ops_raw:
                if isinstance(entry, str):
                    op, topics = entry, None
                elif isinstance(entry, dict) and len(entry) == 1:
                    op, topic_list = next(iter(entry.items()))
                    if isinstance(topic_list, str):
                        topic_list = [topic_list]
                    if not isinstance(topic_list, list):
                        raise ComponentError(
                            f"grants[{comp}] for app {app_id!r}: topic "
                            f"restriction for {op!r} must be a list")
                    topics = [str(t) for t in topic_list]
                else:
                    raise ComponentError(
                        f"grants[{comp}] for app {app_id!r}: each entry "
                        "must be an op string or {op: [topics]}")
                op = str(op)
                if op not in _KNOWN_OPS:
                    raise ComponentError(
                        f"grants[{comp}] for app {app_id!r}: unknown "
                        f"operation {op!r} (known: {sorted(_KNOWN_OPS)})")
                if op in ops and topics is not None and ops[op] is not None:
                    ops[op] = (ops[op] or []) + topics
                else:
                    # an unrestricted grant absorbs a restricted one
                    ops[op] = None if (op in ops and ops[op] is None) else topics
            components[str(comp)] = ops
        return cls(components=components)

    def to_json(self) -> dict:
        return {
            comp: [op if topics is None else {op: topics}
                   for op, topics in ops.items()]
            for comp, ops in self.components.items()
        }

    def check(self, component: str, op: str, *,
              topic: str | None = None, app_id: str | None = None) -> None:
        """Raise PermissionDenied unless ``op`` (optionally on
        ``topic``) is granted for ``component``."""
        ops = self.components.get(component)
        if ops is None or op not in ops:
            raise PermissionDenied(
                f"app {app_id or '?'} has no {op!r} grant on component "
                f"{component!r} (granted: "
                f"{sorted(self.components.get(component, {})) or 'nothing'})")
        topics = ops[op]
        if topics is not None and topic is not None and topic not in topics:
            raise PermissionDenied(
                f"app {app_id or '?'} may {op} on {component!r} only for "
                f"topics {topics}, not {topic!r}")


def grants_from_env() -> AppGrants | None:
    """The orchestrator serialises each app's grants into
    ``TASKSRUNNER_GRANTS`` for its replicas; absent = unrestricted."""
    raw = os.environ.get(GRANTS_ENV)
    if not raw:
        return None
    return AppGrants.parse(json.loads(raw), app_id=os.environ.get(
        "TASKSRUNNER_APP_ID", "?"))


def redact(value: object) -> str:
    """Collapse a secret to a loggable marker: length plus a truncated
    sha256, so two log lines can still be correlated ("same token?")
    without the value ever leaving the process.

    This is the **designated sanitizer** of the tasklint secret-taint
    rule: a value read from a secret store, a token header, or TLS key
    material may only reach a log call, a metric label, a span
    attribute, or an HTTP error body after passing through here (or
    :func:`hash_token`, for full digests that sidecars compare)."""
    import hashlib

    data = value if isinstance(value, bytes) else str(value).encode()
    return f"<redacted len={len(data)} sha256:{hashlib.sha256(data).hexdigest()[:8]}>"


def hash_token(token: str) -> str:
    """sha256 hex digest of a peer token — what sidecars store and
    compare so plaintext peer tokens never leave their own replica."""
    import hashlib

    return hashlib.sha256(token.encode()).hexdigest()


def load_token_map(path: str | pathlib.Path | None = None) -> dict[str, str]:
    """app_id → token **digest** map (``per_app_tokens`` mode).

    The orchestrator writes sha256 digests, not plaintext: every
    replica can verify any inbound peer's token without being able to
    impersonate that peer (a plaintext map would hand every app every
    other app's identity — the opposite of per-app least privilege).
    Empty when the file env/argument is unset; unreadable-as-JSON is
    an error."""
    if path is None:
        path = os.environ.get(TOKENS_FILE_ENV)
    if not path:
        return {}
    p = pathlib.Path(path)
    try:
        doc = json.loads(p.read_text())
    except OSError as exc:
        raise ComponentError(f"cannot read token map {p}: {exc}") from exc
    except ValueError as exc:
        raise ComponentError(f"token map {p} is not valid JSON: {exc}") from exc
    if not isinstance(doc, dict):
        raise ComponentError(f"token map {p} must be a JSON object")
    return {str(k): str(v) for k, v in doc.items()}
