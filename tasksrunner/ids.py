"""Fast unique-id generation for telemetry and message ids.

Trace ids, span ids, CloudEvents ids, and broker message ids need
global uniqueness, not cryptographic unpredictability — they are
correlation keys, never secrets or capabilities. ``secrets.token_hex``
/ ``uuid.uuid4`` pay an ``os.urandom`` syscall per id, which shows up
on the hot path (ids are minted ~5× per end-to-end request: client
span, server span, producer span, CloudEvent id, message id). Here a
process-local PRNG is seeded once from ``os.urandom`` and re-seeded on
fork (pid check), making ids ~5× cheaper with the same collision
characteristics (full-width random values).
"""

from __future__ import annotations

import os
import random
import threading

_local = threading.local()

#: bumped in the child after every fork; a cached rng from another
#: generation is discarded, so a forked worker never replays the
#: parent's stream. Cheaper than the old per-call getpid() syscall —
#: ids are minted several times per request and the syscall dominated.
_generation = 0


def _on_fork() -> None:
    global _generation
    _generation += 1


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_on_fork)


def _rng() -> random.Random:
    rng = getattr(_local, "rng", None)
    if rng is None or getattr(_local, "gen", -1) != _generation:
        # (re)seed from the OS: fresh per thread and per fork
        rng = random.Random(os.urandom(16))
        _local.rng = rng
        _local.gen = _generation
    return rng


def hex8() -> str:
    """16 hex chars (64 random bits) — span-id sized."""
    return f"{_rng().getrandbits(64):016x}"


def hex16() -> str:
    """32 hex chars (128 random bits) — trace-id / message-id sized."""
    return f"{_rng().getrandbits(128):032x}"
