"""Fast unique-id generation for telemetry and message ids.

Trace ids, span ids, CloudEvents ids, and broker message ids need
global uniqueness, not cryptographic unpredictability — they are
correlation keys, never secrets or capabilities. ``secrets.token_hex``
/ ``uuid.uuid4`` pay an ``os.urandom`` syscall per id, which shows up
on the hot path (ids are minted ~5× per end-to-end request: client
span, server span, producer span, CloudEvent id, message id). Here a
process-local PRNG is seeded once from ``os.urandom`` and re-seeded on
fork (pid check), making ids ~5× cheaper with the same collision
characteristics (full-width random values).
"""

from __future__ import annotations

import os
import random
import threading

_local = threading.local()


def _rng() -> random.Random:
    rng = getattr(_local, "rng", None)
    if rng is None or getattr(_local, "pid", -1) != os.getpid():
        # (re)seed from the OS: fresh per thread and per fork, so an
        # orchestrator-forked worker never replays the parent's stream
        rng = random.Random(os.urandom(16))
        _local.rng = rng
        _local.pid = os.getpid()
    return rng


def hex8() -> str:
    """16 hex chars (64 random bits) — span-id sized."""
    return f"{_rng().getrandbits(64):016x}"


def hex16() -> str:
    """32 hex chars (128 random bits) — trace-id / message-id sized."""
    return f"{_rng().getrandbits(128):032x}"
