"""Framework error hierarchy.

Mirrors the failure surface of the reference's runtime (Dapr sidecar
HTTP errors: unknown component, permission/scope denial, malformed
request) so the sidecar API layer can map exceptions to status codes
uniformly.
"""

from __future__ import annotations


class TasksRunnerError(Exception):
    """Base class for all framework errors."""

    #: HTTP status the sidecar API maps this error to.
    http_status = 500


class ValidationError(TasksRunnerError):
    """Client-supplied input is malformed (maps to HTTP 400)."""

    http_status = 400


class ComponentError(TasksRunnerError):
    """A component file or definition is malformed."""

    http_status = 400


class ComponentNotFound(TasksRunnerError):
    """No component with the requested name is registered / in scope.

    The reference's sidecar returns 400 ERR_STATE_STORE_NOT_FOUND /
    ERR_PUBSUB_NOT_FOUND for this case; we use 400 likewise.
    """

    http_status = 400


class ComponentScopeError(TasksRunnerError):
    """Component exists but is not scoped to the calling app-id."""

    http_status = 403


class PermissionDenied(TasksRunnerError):
    """The app's grants do not allow this operation on this component.

    ≙ a missing Azure role assignment in the reference — e.g. a service
    without "Service Bus Data Sender" cannot publish even though the
    component is in scope (webapi-backend-service.bicep:157-165,
    processor-backend-service.bicep:190-198).
    """

    http_status = 403


class DriverNotFound(ComponentError):
    """No driver registered for a component's `type` string."""

    http_status = 400


class SecretError(TasksRunnerError):
    """Secret resolution failed (missing key, missing store...)."""

    http_status = 500


class SecretNotFound(SecretError):
    http_status = 404


class StateError(TasksRunnerError):
    http_status = 500


class EtagMismatch(StateError):
    """Optimistic-concurrency conflict on a state write."""

    http_status = 409


class CrossShardAtomicityError(StateError):
    """A cross-shard state transaction lost atomicity: one or more
    shards committed before a later shard's commit failed, and the
    committed shards cannot be rolled back (SQLite has no distributed
    coordinator log). The message names the committed/uncommitted
    split; the repair is to re-read the affected keys and reconcile.
    Raised only by the sharded facade's two-phase commit path — a
    failure during the *stage* phase, or on the *first* commit, aborts
    cleanly with the original error instead (nothing was durable)."""

    http_status = 500


class ReplicationError(StateError):
    """A replicated-state-plane operation failed (state/replication.py)."""

    http_status = 500


class NotLeaderError(ReplicationError):
    """The write landed on a replica that is not the shard's current
    lease holder. Carries no data loss — nothing was attempted; the
    caller re-resolves the leader and retries (the facade does this
    once automatically). Maps to 409 like the other ownership
    conflicts."""

    http_status = 409


class ReplicaFencedError(ReplicationError):
    """A leader's commit was rejected by epoch fencing.

    A follower that promoted itself bumped the shard epoch, so the old
    leader's late records carry a stale epoch and every follower
    refuses them — the write can no longer reach its ack quorum and
    was NEVER acked. Same contract as :class:`ActorFencedError`, one
    layer down: zombies fail closed."""

    http_status = 409


class ReplicationQuorumError(ReplicationError):
    """An acked-after-replication write could not reach its configured
    ``ackQuorum`` within the ack timeout. The record is committed on
    the leader's copy but its durability on followers is UNKNOWN — the
    caller must treat the write as not acked (retry is safe: records
    are idempotent by sequence number). Maps to 503: the replica set
    is degraded, not the request malformed."""

    http_status = 503


class ReplicationGapError(ReplicationError):
    """Protocol signal from a follower: the appended record does not
    extend its log (``seq`` beyond ``hwm + 1``, or a diverged suffix
    from a fenced ex-leader). The leader answers with a log catch-up
    from ``hwm``, or a full snapshot when ``diverged`` (or the log was
    pruned past the gap). Never surfaces to state-API callers."""

    def __init__(self, message: str, *, hwm: int, diverged: bool = False):
        super().__init__(message)
        self.hwm = hwm
        self.diverged = diverged


class StaleReadError(ReplicationError):
    """A follower read was refused because the replica's lag exceeded
    the configured bound (``maxLagRecords``). The facade redirects to
    the leader instead of surfacing this; it reaches callers only when
    they address a follower directly."""

    http_status = 503


class PlacementEpochError(StateError):
    """A state request carried a routing-table epoch that does not
    match the store's current placement epoch.

    Every elastic-placement flip (live migration, shard split) bumps
    the :class:`~tasksrunner.state.placement.PlacementMap` epoch, and
    the sidecar validates the caller's ``x-tasksrunner-placement-epoch``
    header against it on every state request. A mismatch means the
    caller routed with a stale (or not-yet-seen) table; nothing was
    attempted, so nothing can be lost — the 409 response carries the
    server's current epoch and the client refreshes its map and
    retries. Same fail-closed contract as :class:`NotLeaderError`, one
    layer up: routing races surface as redirects, never as writes
    applied at the wrong shard."""

    http_status = 409

    def __init__(self, message: str, *, current_epoch: int):
        super().__init__(message)
        self.current_epoch = int(current_epoch)


class QueryError(StateError):
    """Malformed state query or store without query support.

    The reference hits this when querying a non-query-capable store
    (plain Redis) — docs/aca/04-aca-dapr-stateapi/index.md:166-168.
    """

    http_status = 400


class PubSubError(TasksRunnerError):
    http_status = 500


class BindingError(TasksRunnerError):
    http_status = 500


class InvocationError(TasksRunnerError):
    """Service invocation failed (unknown app-id, connection refused)."""

    http_status = 500


class InvocationStatusError(InvocationError):
    """The invocation target ANSWERED, with a non-2xx status — raised by
    ``raise_for_status``. Distinct from its parent so callers can tell
    "the backend is down" from "the backend rejected the request"
    without parsing the message."""

    def __init__(self, message: str, *, status: int):
        super().__init__(message)
        self.status = status


class ChaosInjectedError(TasksRunnerError):
    """A fault injected by the chaos subsystem (``TASKSRUNNER_CHAOS=1``).

    Raised only when an operator has declared a ``kind: Chaos`` document
    and enabled the gate — never on a production path. Status-mode
    faults carry the synthesized HTTP status so the sidecar API maps
    the injection to exactly the declared code.
    """

    def __init__(self, message: str, *, status: int | None = None):
        super().__init__(message)
        if status is not None:
            self.http_status = status
        self.status = status


class SaturatedError(TasksRunnerError):
    """The replica's admission controller shed this request (429).

    The server is alive but refusing non-exempt work until its
    saturation score drops (observability/admission.py). When the 429
    carried a ``Retry-After`` header, :attr:`retry_after` holds it in
    seconds and the resiliency retry loop stretches its next delay to
    honor it (still inside the policy's total budget)."""

    http_status = 429
    #: seconds the server asked us to stay away, or None
    retry_after: float | None = None


class ActorError(TasksRunnerError):
    """A virtual-actor operation failed (tasksrunner/actors/)."""

    http_status = 500


class ActorNotRegistered(ActorError):
    """The app hosts no handler for the requested actor type."""

    http_status = 404


class ActorFencedError(ActorError):
    """A turn's commit was rejected by epoch fencing.

    Every ownership acquisition bumps the actor record's epoch with an
    etag-guarded write, so a zombie owner — one that lost its lease
    mid-turn, or a crashed-but-still-scheduled replica — commits with
    a stale etag and lands here instead of corrupting state. The turn
    was NOT applied and was never acked; callers retry against the new
    owner. Maps to 409 like the underlying :class:`EtagMismatch`."""

    http_status = 409


class CircuitOpenError(TasksRunnerError):
    """A resiliency circuit breaker is open — the call was rejected
    without being attempted (fail-fast). Maps to 503 so callers can
    distinguish "target is being protected" from a target-side 5xx."""

    http_status = 503


class AppNotFound(InvocationError):
    """Name resolution failed for a target app-id."""

    http_status = 404


class PortInUseError(TasksRunnerError):
    """A server socket could not bind because the port is taken.

    Raised instead of the raw OSError so operators get one clean line
    naming the port and the usual causes (another replica, a leftover
    process) rather than a runpy traceback — the failure every
    workshop attendee hits at least once."""


class WorkflowError(TasksRunnerError):
    """A durable-workflow operation failed (tasksrunner/workflows/)."""

    http_status = 500


class WorkflowNotFound(WorkflowError):
    """No workflow instance (or registered workflow name) matches."""

    http_status = 404


class WorkflowNondeterminismError(WorkflowError):
    """Replay diverged from the recorded history.

    The orchestrator scheduled different work on re-execution than the
    history records (a different activity name at the same sequence
    number, or fewer steps than events). That means the function read
    something outside the workflow context — wall clock, randomness,
    environment, live state — and its past decisions can no longer be
    reconstructed. The instance is faulted rather than allowed to
    re-run side effects; the workflow-determinism lint rule exists to
    catch the mistake before it ships."""

    http_status = 500


class ActivityError(WorkflowError):
    """An activity exhausted its retry policy (or failed with a
    non-retriable error). Awaiting the activity's task inside the
    orchestrator raises this — catchable there, so a saga can branch
    into its compensation path."""

    http_status = 500
