"""The runtime-side workflow plane: start/status/terminate/raise-event.

``WorkflowRuntime`` is a thin client over the actor runtime — every
operation is an actor turn on the ``_Workflow`` instance, so it
inherits single-owner serialization, ack-after-commit, and fencing
without any machinery of its own. What it adds is the *pump*: a turn's
result doc says whether more work is immediately available
(``outcome == "running"``), which children need starting, and which
parent needs notifying — the pump drains those until the instance
blocks or terminates.

The pump is an accelerator, not a correctness dependency: a running
instance always carries the periodic ``__wfdrive`` reminder, so even
with every pump gone (the owner crashed), any surviving replica's
sweep adopts the instance and each reminder firing advances it one
batch. The registered turn observer re-attaches a pump after adoption,
so recovery converges at pump speed, not sweep speed.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import time
import uuid
from typing import Any

from tasksrunner.errors import TasksRunnerError, WorkflowNotFound
from tasksrunner.workflows.engine import WORKFLOW_ACTOR_TYPE

logger = logging.getLogger(__name__)

_TERMINAL = ("completed", "failed", "terminated")

#: pump safety valve: a single follow-up chain never issues more than
#: this many step turns (a buggy orchestrator that always reports
#: "running" must not wedge the caller forever)
_MAX_PUMP_STEPS = 10_000


class WorkflowRuntime:
    """One replica's handle on the workflow plane."""

    def __init__(self, runtime: Any, actors: Any):
        self.runtime = runtime
        self.actors = actors
        self._observer = self._on_reminder_turn
        actors.turn_observers.append(self._observer)
        #: background child-start / pump tasks (kept to a set so they
        #: are not garbage-collected mid-flight)
        self._tasks: set[asyncio.Task] = set()

    def detach(self) -> None:
        with contextlib.suppress(ValueError):
            self.actors.turn_observers.remove(self._observer)
        for task in self._tasks:
            task.cancel()
        self._tasks.clear()

    # -- public operations -------------------------------------------------

    async def start(self, name: str, input: Any = None, *,
                    instance: str | None = None,
                    parent: dict | None = None) -> str:
        """Start (or idempotently re-start) an instance; returns its id
        after the start turn committed."""
        instance = instance or uuid.uuid4().hex
        doc = await self._turn(instance, "start", {
            "wf": name, "input": input, "parent": parent})
        await self._follow_up(instance, doc)
        return instance

    async def status(self, instance: str) -> dict:
        """Durable status — a plain state read, served by any replica."""
        record = await self.actors.read_state(WORKFLOW_ACTOR_TYPE, instance)
        state = record.get("data") or {}
        if not state.get("wf"):
            raise WorkflowNotFound(
                f"no workflow instance {instance!r}")
        return {
            "instance": instance,
            "workflow": state.get("wf"),
            "status": state.get("status"),
            "result": state.get("result"),
            "error": state.get("error"),
            "events": len(state.get("history") or ()),
            "created": state.get("created"),
            "updated": state.get("updated"),
            "parent": (state.get("parent") or {}).get("instance"),
        }

    async def history(self, instance: str) -> list[dict]:
        record = await self.actors.read_state(WORKFLOW_ACTOR_TYPE, instance)
        state = record.get("data") or {}
        if not state.get("wf"):
            raise WorkflowNotFound(f"no workflow instance {instance!r}")
        return list(state.get("history") or ())

    async def raise_event(self, instance: str, event: str,
                          data: Any = None, *, id: str | None = None) -> dict:
        doc = await self._turn(instance, "raise",
                               {"name": event, "data": data, "id": id})
        await self._follow_up(instance, doc)
        return doc

    async def terminate(self, instance: str,
                        reason: str = "terminated") -> dict:
        doc = await self._turn(instance, "terminate", {"reason": reason})
        await self._follow_up(instance, doc)
        return doc

    async def wait(self, instance: str, *, timeout: float = 30.0,
                   poll: float = 0.05) -> dict:
        """Poll until the instance reaches a terminal status."""
        deadline = time.monotonic() + timeout
        while True:
            status = await self.status(instance)
            if status["status"] in _TERMINAL:
                return status
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"workflow {instance!r} still {status['status']!r} "
                    f"after {timeout}s")
            await asyncio.sleep(poll)

    async def list(self) -> list[dict]:
        """Every known instance (from the actor index), oldest first."""
        rows = []
        for instance in await self.actors._index_ids(WORKFLOW_ACTOR_TYPE):
            try:
                rows.append(await self.status(instance))
            except WorkflowNotFound:
                continue  # GC'd or never-started record
        rows.sort(key=lambda r: r.get("created") or 0.0)
        return rows

    def summary(self) -> dict:
        """Cheap local view for ``/v1.0/metadata``."""
        return {"actor_type": WORKFLOW_ACTOR_TYPE,
                "pumps_in_flight": len(self._tasks)}

    # -- the pump ----------------------------------------------------------

    async def _turn(self, instance: str, method: str, data: Any) -> dict:
        doc = await self.actors.invoke_turn(
            WORKFLOW_ACTOR_TYPE, instance, method, data)
        return doc if isinstance(doc, dict) else {}

    async def _follow_up(self, instance: str, doc: dict) -> None:
        """Drain immediately-available work: step while the turn
        reports ``running``, start children, deliver the parent
        notification, reconcile possibly-lost child completions."""
        steps = 0
        while doc:
            await self._side_actions(instance, doc)
            if doc.get("outcome") != "running" or steps >= _MAX_PUMP_STEPS:
                return
            steps += 1
            try:
                doc = await self._turn(instance, "step", None)
            except TasksRunnerError as exc:
                # owner moved or crashed mid-pump: the drive reminder
                # (wherever the instance lands next) takes over
                logger.debug("pump for %s stopped: %s", instance, exc)
                return

    async def _side_actions(self, instance: str, doc: dict) -> None:
        for child in doc.get("start_children") or []:
            self._spawn(self._start_child(child))
        notify = doc.get("notify_parent")
        if notify is not None:
            self._spawn(self._notify_parent(notify))
        for pending in doc.get("pending_children") or []:
            self._spawn(self._reconcile_child(instance, pending))

    def _spawn(self, coro) -> None:
        task = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._reap)

    def _reap(self, task: asyncio.Task) -> None:
        self._tasks.discard(task)
        if task.cancelled():
            return
        exc = task.exception()  # retrieve: a crash drill killing a pump
        if exc is not None:     # task must not warn at GC time
            logger.debug("workflow pump task died: %r", exc)

    async def _start_child(self, child: dict) -> None:
        try:
            await self.start(child["wf"], child.get("input"),
                             instance=child["instance"],
                             parent=child.get("parent"))
        except TasksRunnerError as exc:
            # the parent re-requests the start on its next turn
            logger.warning("starting child workflow %s failed: %s",
                           child.get("instance"), exc)

    async def _notify_parent(self, notify: dict) -> None:
        try:
            await self.raise_event(notify["instance"], notify["event"],
                                   data=notify.get("data"),
                                   id=notify.get("id"))
        except TasksRunnerError as exc:
            # lost notification: the parent's pending-children
            # reconciliation polls the child state and self-heals
            logger.warning("notifying parent %s failed: %s",
                           notify.get("instance"), exc)

    async def _reconcile_child(self, parent: str, pending: dict) -> None:
        """If a child already terminated but the parent never saw it
        (its completion notification died with a crashed replica),
        re-deliver from the child's durable state."""
        child = pending["instance"]
        try:
            record = await self.actors.read_state(WORKFLOW_ACTOR_TYPE, child)
        except TasksRunnerError:
            return
        state = record.get("data") or {}
        if state.get("status") not in _TERMINAL:
            return
        data = ({"error": state.get("error")}
                if state["status"] in ("failed", "terminated")
                else {"result": state.get("result")})
        with contextlib.suppress(TasksRunnerError):
            await self.raise_event(parent, pending["event"], data=data,
                                   id=f"{child}::done")

    # -- reminder-driven progress ------------------------------------------

    async def _on_reminder_turn(self, actor_type: str, actor_id: str,
                                method: str, result: Any) -> None:
        """Called by the actor sweep after a reminder turn committed.
        This is how an ADOPTED instance (original owner dead, no pump
        anywhere) gets a pump again on the adopting replica."""
        if actor_type != WORKFLOW_ACTOR_TYPE or not isinstance(result, dict):
            return
        await self._follow_up(actor_id, result)
