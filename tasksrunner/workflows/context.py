"""The deterministic orchestrator surface: tasks and the context.

An orchestrator function is replayed from the start of its history on
every scheduling turn, so everything it can observe must be a pure
function of that history: activity results, timer firings, external
events, child results — plus the deterministic substitutes the context
provides for the ambient sources replay would otherwise diverge on
(:meth:`WorkflowContext.now`, :meth:`WorkflowContext.random`,
:meth:`WorkflowContext.uuid4`). The ``workflow-determinism`` tasklint
rule flags direct wall-clock / random / uuid / env reads and component
calls inside orchestrators; all side effects belong in activities.

The replay driver (engine.py) exploits one property of the task
awaitables here: awaiting a task whose outcome is already recorded in
history never suspends — ``__await__`` returns (or raises) inline — so
a single ``coro.send(None)`` runs the orchestrator up to its first
*unresolved* await, and every task created before that point is the
schedulable frontier. Fan-out falls out for free: tasks are scheduled
at creation time, not at await time.
"""

from __future__ import annotations

import random
import uuid
from collections import deque
from typing import Any, Iterable

from tasksrunner.errors import ActivityError, WorkflowNondeterminismError

#: history event names a task resolves from, by task kind
_ACTIVITY_EVENTS = ("activity_completed", "activity_failed")

#: internal event-name prefix carrying a child workflow's outcome back
#: to the parent task that scheduled it (suffix = the task's seq)
CHILD_EVENT_PREFIX = "__wfchild::"


class _WorkflowTask:
    """One schedulable unit: an activity call, a durable timer, an
    external-event wait, a child workflow, or a when_all/when_any
    composite. Awaitable exactly once per replay."""

    __slots__ = ("kind", "seq", "name", "payload", "resolved", "value",
                 "error", "children", "fire_at", "resolved_pos")

    def __init__(self, kind: str, seq: int | None, name: str = "", *,
                 payload: Any = None, fire_at: float | None = None,
                 children: list["_WorkflowTask"] | None = None):
        self.kind = kind       # activity | timer | event | child | all | any
        self.seq = seq         # None for composites
        self.name = name
        self.payload = payload
        self.resolved = False
        self.value: Any = None
        self.error: str | None = None
        self.children = children or []
        self.fire_at = fire_at
        #: history position of the resolving event — when_any picks its
        #: winner by this, so the verdict of a race is frozen the moment
        #: the first competitor's event lands in history and can never
        #: flip when a later event resolves an earlier-listed task
        self.resolved_pos = -1

    def resolve(self, value: Any) -> None:
        self.resolved = True
        self.value = value

    def fail(self, error: str) -> None:
        self.resolved = True
        self.error = error

    def _outcome(self) -> Any:
        if self.error is not None:
            raise ActivityError(self.error)
        return self.value

    def __await__(self):
        if not self.resolved:
            yield self  # suspends the replay; the driver never resumes
        if not self.resolved:
            raise WorkflowNondeterminismError(
                "a workflow task was resumed without a recorded outcome "
                "(tasks must only be awaited inside an orchestrator)")
        return self._outcome()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        state = "resolved" if self.resolved else "pending"
        return f"<_WorkflowTask {self.kind} #{self.seq} {self.name!r} {state}>"


class ActivityContext:
    """What an activity function receives beside its input."""

    __slots__ = ("instance", "workflow", "name", "seq", "attempt",
                 "is_compensation", "effects")

    def __init__(self, *, instance: str, workflow: str, name: str,
                 seq: int, attempt: int, is_compensation: bool = False):
        self.instance = instance
        self.workflow = workflow
        self.name = name
        self.seq = seq
        self.attempt = attempt
        self.is_compensation = is_compensation
        #: staged state ops, applied atomically WITH the history commit
        #: that records this activity's completion — which is exactly
        #: why an acked effect is applied once even when the activity
        #: body ran more than once (crash before commit = no effect)
        self.effects: list[dict] = []

    def stage_effect(self, key: str, value: Any = None, *,
                     operation: str = "upsert") -> None:
        """Stage a write to the workflow state store, committed
        atomically with this activity's completion event."""
        if operation not in ("upsert", "delete"):
            raise ValueError(f"unknown effect operation {operation!r}")
        self.effects.append(
            {"operation": operation, "key": str(key), "value": value})


class WorkflowContext:
    """The orchestrator's only legitimate window on the world."""

    def __init__(self, *, instance: str, workflow: str,
                 history: list[dict], input: Any = None):
        self.instance = instance
        self.workflow = workflow
        self.input = input
        #: False from the first await whose outcome history does NOT
        #: already hold — i.e. True exactly while re-traversing old
        #: ground (use it to suppress duplicate logging, nothing else)
        self.is_replaying = True
        self.tasks: list[_WorkflowTask] = []
        #: (activity name, input) pairs in registration order; the
        #: engine runs them in reverse when the orchestrator fails
        self.compensations: list[tuple[str, Any]] = []
        self._seq = 0
        self._results: dict[int, dict] = {}
        self._event_queues: dict[str, deque] = {}
        self._now = 0.0
        self._rng = random.Random(f"wf:{workflow}:{instance}")
        for pos, event in enumerate(history):
            t = event.get("t")
            ts = float(event.get("ts", 0.0))
            if t == "started":
                self._now = max(self._now, ts)
            elif t in _ACTIVITY_EVENTS or t == "timer_fired":
                self._results[int(event["seq"])] = {**event, "_pos": pos}
            elif t == "event_raised":
                name = str(event.get("name") or "")
                if name.startswith(CHILD_EVENT_PREFIX):
                    try:
                        seq = int(name[len(CHILD_EVENT_PREFIX):])
                    except ValueError:
                        continue
                    self._results[seq] = {**event, "t": "child_done",
                                          "_pos": pos}
                else:
                    self._event_queues.setdefault(name, deque()).append(
                        {**event, "_pos": pos})

    # -- deterministic ambient substitutes --------------------------------

    def now(self) -> float:
        """The timestamp of the latest history event applied so far —
        the replay-stable stand-in for ``time.time()``."""
        return self._now

    def random(self) -> float:
        """Replay-stable ``random.random()`` (seeded per instance)."""
        return self._rng.random()

    def uuid4(self) -> str:
        """Replay-stable uuid4 string (drawn from the instance PRNG)."""
        return str(uuid.UUID(int=self._rng.getrandbits(128), version=4))

    # -- task creation -----------------------------------------------------

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _attach(self, task: _WorkflowTask) -> _WorkflowTask:
        self.tasks.append(task)
        rec = self._results.get(task.seq)
        if rec is None:
            if task.kind == "event":
                queue = self._event_queues.get(task.name)
                if queue:
                    event = queue.popleft()
                    self._now = max(self._now, float(event.get("ts", 0.0)))
                    task.resolve(event.get("data"))
                    task.resolved_pos = int(event.get("_pos", -1))
                    return task
            self.is_replaying = False
            return task
        t = rec.get("t")
        expect = {"activity": _ACTIVITY_EVENTS, "timer": ("timer_fired",),
                  "child": ("child_done",)}.get(task.kind, ())
        if t not in expect:
            raise WorkflowNondeterminismError(
                f"workflow {self.workflow!r} instance {self.instance!r}: "
                f"replay scheduled a {task.kind!r} task at seq {task.seq} "
                f"but history recorded {t!r} there — the orchestrator is "
                "not deterministic")
        if t in _ACTIVITY_EVENTS and rec.get("name") != task.name:
            raise WorkflowNondeterminismError(
                f"workflow {self.workflow!r} instance {self.instance!r}: "
                f"replay called activity {task.name!r} at seq {task.seq} "
                f"but history recorded {rec.get('name')!r} there — the "
                "orchestrator is not deterministic")
        self._now = max(self._now, float(rec.get("ts", 0.0)))
        if t == "activity_completed":
            task.resolve(rec.get("result"))
        elif t == "activity_failed":
            task.fail(str(rec.get("error")))
        elif t == "timer_fired":
            task.resolve(None)
        else:  # child_done
            data = rec.get("data") or {}
            if data.get("error") is not None:
                task.fail(str(data["error"]))
            else:
                task.resolve(data.get("result"))
        task.resolved_pos = int(rec.get("_pos", -1))
        return task

    def call_activity(self, name: str, input: Any = None) -> _WorkflowTask:
        """Schedule one activity execution. The returned task resolves
        with the activity's result (or raises :class:`ActivityError`
        once its retry policy is exhausted)."""
        return self._attach(_WorkflowTask(
            "activity", self._next_seq(), name, payload=input))

    def create_timer(self, delay_seconds: float) -> _WorkflowTask:
        """A durable timer: fires ``delay_seconds`` after the moment it
        was scheduled (in history time), surviving host loss via the
        reminder machinery."""
        return self._attach(_WorkflowTask(
            "timer", self._next_seq(),
            fire_at=self._now + max(0.0, float(delay_seconds))))

    def sleep(self, delay_seconds: float) -> _WorkflowTask:
        return self.create_timer(delay_seconds)

    def wait_event(self, name: str) -> _WorkflowTask:
        """Wait for an external event raised at this instance. Events
        raised before the wait are buffered; each wait consumes one
        raising, FIFO per name."""
        if name.startswith(CHILD_EVENT_PREFIX):
            raise WorkflowNondeterminismError(
                f"event name {name!r} uses the reserved child-completion "
                "prefix")
        return self._attach(_WorkflowTask("event", self._next_seq(), name))

    def call_child(self, name: str, input: Any = None, *,
                   instance: str | None = None) -> _WorkflowTask:
        """Schedule a child workflow. Its instance id is derived from
        this instance and the task seq unless given, so replays (and
        crash-retried starts) address the SAME child — starts are
        idempotent on the child side."""
        seq = self._next_seq()
        task = _WorkflowTask("child", seq, name, payload={
            "input": input,
            "instance": instance or f"{self.instance}::c{seq}",
        })
        return self._attach(task)

    def register_compensation(self, name: str, input: Any = None) -> None:
        """Register a compensating activity. On orchestrator failure
        the engine runs every registered compensation exactly once, in
        reverse registration order."""
        self.compensations.append((name, input))

    # -- composition -------------------------------------------------------

    def when_all(self, tasks: Iterable[_WorkflowTask]) -> _WorkflowTask:
        """Resolves with the list of results once every task resolved;
        fails (with the first failed task's error, in list order) once
        all resolved and any failed."""
        children = list(tasks)
        comp = _WorkflowTask("all", None, children=children)
        if all(t.resolved for t in children):
            failed = next((t for t in children if t.error is not None), None)
            if failed is not None:
                comp.fail(failed.error)
            else:
                comp.resolve([t.value for t in children])
        self.tasks.append(comp)
        return comp

    def when_any(self, tasks: Iterable[_WorkflowTask]) -> _WorkflowTask:
        """Resolves with the winning task object — the one whose
        outcome landed in history FIRST (history position, a pure
        function of the log, so replay-stable even when a slower
        competitor's event arrives later). Lets an orchestrator race an
        activity against a timer."""
        children = list(tasks)
        comp = _WorkflowTask("any", None, children=children)
        resolved = [t for t in children if t.resolved]
        if resolved:
            comp.resolve(min(resolved, key=lambda t: t.resolved_pos))
        self.tasks.append(comp)
        return comp
