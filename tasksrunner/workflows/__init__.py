"""Durable replay-based workflows on the actor runtime.

See docs/modules/21-workflows.md. An orchestrator is a deterministic
``async def (ctx, input)`` replayed against its committed event
history; activities carry the side effects (with per-activity retry
policies), compensations give saga semantics, timers ride the durable
reminder machinery, and every scheduling turn commits atomically on
the actor state plane — which is what makes the whole thing survive
``kill -9`` between (and during) steps.
"""

from tasksrunner.workflows.context import (
    ActivityContext,
    WorkflowContext,
)
from tasksrunner.workflows.engine import (
    DRIVE_REMINDER,
    GC_REMINDER,
    WORKFLOW_ACTOR_TYPE,
    WorkflowEngine,
)
from tasksrunner.workflows.runtime import WorkflowRuntime

__all__ = [
    "ActivityContext",
    "DRIVE_REMINDER",
    "GC_REMINDER",
    "WORKFLOW_ACTOR_TYPE",
    "WorkflowContext",
    "WorkflowEngine",
    "WorkflowRuntime",
]
