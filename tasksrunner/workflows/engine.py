"""The workflow engine: replay, scheduling, sagas — all inside actor turns.

Every workflow instance IS a virtual actor (type ``_Workflow``, id =
instance id), which buys the whole durability story for free:

* **Single writer.** The actor's one-turn-at-a-time lock plus epoch
  fencing mean exactly one live replica appends to an instance's
  history; a zombie's commit dies on the etag chain.
* **Atomic progress.** One scheduling turn = one commit: the history
  events appended this turn, the activity effects they record, and the
  reminder changes all land in a single etag-guarded store transaction
  (``ActorRuntime._commit`` with effects). A crash mid-turn loses the
  whole turn — the activities re-execute on replay (at-least-once
  bodies), but their *effects* apply exactly once, because an effect
  only exists in the same transaction as the event recording it.
* **Automatic recovery.** The periodic ``__wfdrive`` reminder makes a
  running instance adoptable: when its owner dies, a surviving
  replica's sweep adopts the actor, fires the reminder, and the replay
  converges from the committed history prefix.

The orchestrator function itself is driven by ONE ``coro.send(None)``
per replay: awaiting a task with a recorded outcome never suspends
(see context.py), so the coroutine runs to its first unresolved await
and every unresolved task created before that point is the schedulable
frontier. ``TASKSRUNNER_WORKFLOW_REPLAY_BATCH`` bounds how many
activities one turn executes — the knob that trades turn length
against replayed work after a crash.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, Callable

from tasksrunner.errors import (
    ActivityError,
    WorkflowError,
    WorkflowNondeterminismError,
    WorkflowNotFound,
)
from tasksrunner.ids import hex8, hex16
from tasksrunner.observability.metrics import metrics
from tasksrunner.observability.spans import active as spans_active, record_span
from tasksrunner.observability.tracing import (
    TraceContext,
    current_trace,
    trace_scope,
)
from tasksrunner.resiliency.policy import RetrySpec
from tasksrunner.workflows.context import (
    CHILD_EVENT_PREFIX,
    ActivityContext,
    WorkflowContext,
    _WorkflowTask,
)

logger = logging.getLogger(__name__)

#: the actor type every workflow instance lives under
WORKFLOW_ACTOR_TYPE = "_Workflow"
#: periodic reminder that keeps a running instance adoptable + driven
DRIVE_REMINDER = "__wfdrive"
#: one-shot reminder that truncates a terminal instance's history
GC_REMINDER = "__wfgc"

_TERMINAL = ("completed", "failed", "terminated")

DEFAULT_RETRY = RetrySpec(policy="exponential", duration=0.2,
                          max_interval=5.0, max_retries=3)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r; using %s",
                       name, raw, default)
        return default


class _WorkflowCrashed(BaseException):
    """A crash-mode chaos fault fell this replica mid-activity. A
    BaseException on purpose: it must sail past every except-Exception
    net (retry loops, the actor turn handler) so the turn dies WITHOUT
    committing — exactly what SIGKILL would have done."""


class WorkflowEngine:
    """App-side registries plus the turn handler for ``_Workflow``."""

    def __init__(self, app: Any):
        self.app = app
        self.workflows: dict[str, Callable] = {}
        #: name → (fn, RetrySpec, per-attempt timeout seconds)
        self.activities: dict[str, tuple] = {}
        #: runtime-side wiring, pushed in by Runtime._start_workflows
        self.chaos = None
        self.crash_on_chaos = False
        self.crash_hook: Callable[[], None] | None = None
        self.drive_period = 2.0
        self.replay_batch = max(1, int(_env_float(
            "TASKSRUNNER_WORKFLOW_REPLAY_BATCH", 16)))
        self.default_timeout = _env_float(
            "TASKSRUNNER_WORKFLOW_ACTIVITY_TIMEOUT_SECONDS", 30.0)
        self.retain_seconds = _env_float(
            "TASKSRUNNER_WORKFLOW_HISTORY_RETAIN_SECONDS", 3600.0)

    # -- registration ------------------------------------------------------

    def register_workflow(self, name: str, fn: Callable) -> None:
        if name in self.workflows:
            raise WorkflowError(f"workflow {name!r} is already registered")
        self.workflows[name] = fn

    def register_activity(self, name: str, fn: Callable, *,
                          retry: RetrySpec | None = None,
                          timeout: float | None = None) -> None:
        if name in self.activities:
            raise WorkflowError(f"activity {name!r} is already registered")
        self.activities[name] = (fn, retry or DEFAULT_RETRY,
                                 timeout or self.default_timeout)

    # -- the actor turn handler --------------------------------------------

    async def handle_turn(self, turn: Any) -> dict:  # tasklint: fenced-lane
        """Every workflow operation is an actor turn on the instance —
        serialized by the actor lock, committed atomically, fenced."""
        method = turn.method
        if turn.is_reminder:
            if method == GC_REMINDER:
                return self._gc(turn)
            return await self._advance(turn)  # DRIVE_REMINDER
        if method == "start":
            return await self._start(turn)
        if method == "step":
            return await self._advance(turn)
        if method == "raise":
            return await self._raise(turn)
        if method == "terminate":
            return await self._terminate(turn)
        raise WorkflowError(f"unknown workflow method {method!r}")

    # -- operations --------------------------------------------------------

    async def _start(self, turn: Any) -> dict:
        data = turn.data or {}
        name = str(data.get("wf") or "")
        if name not in self.workflows:
            raise WorkflowNotFound(
                f"no workflow named {name!r} is registered "
                f"(known: {sorted(self.workflows) or 'none'})")
        if turn.state.get("wf"):
            # idempotent restart: crash-retried starts and replayed
            # child starts land here — report, don't reinitialize
            return self._doc(turn, outcome=self._outcome_of(turn.state))
        ts = time.time()
        turn.state.update({
            "wf": name,
            "input": data.get("input"),
            "status": "running",
            "history": [{"t": "started", "ts": ts}],
            "created": ts,
            "updated": ts,
            "parent": data.get("parent"),
            "result": None,
            "error": None,
        })
        turn.set_reminder(DRIVE_REMINDER, self.drive_period,
                          period_seconds=self.drive_period)
        metrics.inc("workflow_started_total", workflow=name)
        return await self._advance(turn)

    async def _raise(self, turn: Any) -> dict:
        state = turn.state
        if not state.get("wf"):
            raise WorkflowNotFound(
                f"workflow instance {turn.actor_id!r} was never started")
        if state.get("status") in _TERMINAL:
            return self._doc(turn, outcome=state["status"])
        data = turn.data or {}
        name = str(data.get("name") or "")
        event_id = data.get("id")
        if event_id is not None:
            for event in state["history"]:
                if (event.get("t") == "event_raised"
                        and event.get("id") == event_id):
                    # duplicate delivery (a retried child notification):
                    # drop it, then still advance — idempotent
                    return await self._advance(turn)
        state["history"].append({
            "t": "event_raised", "ts": time.time(), "name": name,
            "data": data.get("data"), "id": event_id,
        })
        return await self._advance(turn)

    async def _terminate(self, turn: Any) -> dict:
        state = turn.state
        if not state.get("wf"):
            raise WorkflowNotFound(
                f"workflow instance {turn.actor_id!r} was never started")
        if state.get("status") in _TERMINAL:
            return self._doc(turn, outcome=state["status"])
        reason = str((turn.data or {}).get("reason") or "terminated")
        state["history"].append(
            {"t": "terminated", "ts": time.time(), "reason": reason})
        self._finalize(turn, "terminated", error=reason)
        return self._doc(turn, outcome="terminated")

    def _gc(self, turn: Any) -> dict:
        """Truncate a terminal instance's history to its last event (a
        summary stub). The GC reminder is one-shot: the runtime already
        popped it when it fired."""
        state = turn.state
        if state.get("status") in _TERMINAL and state.get("history"):
            dropped = len(state["history"]) - 1
            state["history"] = state["history"][-1:]
            state["gc_dropped_events"] = dropped
        return self._doc(turn, outcome=self._outcome_of(state))

    # -- the scheduler -----------------------------------------------------

    def _instance_trace(self, state: dict) -> dict | None:
        """The instance's durable trace identity. Created on the first
        traced turn (normally the start turn, whose caller context it
        joins) and carried in actor state like the history, so the
        replica that adopts the instance after its owner dies keeps
        appending to the SAME logical trace — replays and crashes
        stitch instead of fragmenting into per-owner traces."""
        if not spans_active():
            return None
        trace = state.get("trace")
        if trace is None:
            ctx = current_trace()
            trace = {"id": ctx.trace_id if ctx is not None else hex16(),
                     "root": hex8(),
                     "parent": ctx.span_id if ctx is not None else None}
            state["trace"] = trace
        return trace

    def _child_span(self, *, name: str, status: int, start: float,
                    duration: float, attrs: dict) -> None:
        """A span nested under the current workflow-turn span. Explicit
        ids on purpose: the ambient span IS the turn span, so letting
        record_span default would collide with it."""
        if not spans_active():
            return
        ctx = current_trace()
        if ctx is None:
            return
        record_span(kind="internal", name=name, status=status, start=start,
                    duration=duration, attrs=attrs, trace_id=ctx.trace_id,
                    span_id=hex8(), parent_id=ctx.span_id)

    async def _advance(self, turn: Any) -> dict:
        state = turn.state
        if not state.get("wf"):
            # adopted before start committed, or a stray reminder after
            # GC of an unstarted record — nothing to do
            return self._doc(turn, outcome="noop")
        if state.get("status") in _TERMINAL:
            turn.clear_reminder(DRIVE_REMINDER)
            return self._doc(turn, outcome=state["status"])
        trace = self._instance_trace(state)
        if trace is None:
            return await self._drive(turn)
        # One span per scheduling turn, recorded with an explicit
        # trace_id: the ambient context belongs to whichever caller or
        # reminder drove this turn, but the span belongs to the
        # instance's own trace. Replay passes inside the turn are NOT
        # separate spans — history replay re-executes nothing, so the
        # turn span just carries the event count it replayed over.
        if trace.get("rooted"):
            turn_ctx = TraceContext(trace_id=trace["id"], span_id=hex8(),
                                    parent_id=trace["root"])
        else:
            # the first traced turn IS the instance's root span
            turn_ctx = TraceContext(trace_id=trace["id"],
                                    span_id=trace["root"],
                                    parent_id=trace.get("parent"))
            trace["rooted"] = True
        started = time.time()
        perf = time.perf_counter()
        outcome = "error"
        try:
            with trace_scope(turn_ctx):
                doc = await self._drive(turn)
                outcome = doc.get("outcome") or "ok"
                return doc
        finally:
            record_span(
                kind="internal", name=f"workflow-turn {state['wf']}",
                status=200 if outcome != "error" else 500,
                start=started, duration=time.perf_counter() - perf,
                attrs={"instance": turn.actor_id, "outcome": outcome,
                       "events": len(state.get("history") or ())},
                trace_id=trace["id"], span_id=turn_ctx.span_id,
                parent_id=turn_ctx.parent_id)

    async def _drive(self, turn: Any) -> dict:
        state = turn.state
        wf_name = state["wf"]
        orchestrator = self.workflows.get(wf_name)
        if orchestrator is None:
            # host rolled forward without this workflow registered:
            # leave the instance intact for a replica that has it
            logger.warning("instance %s references unregistered workflow %r",
                           turn.actor_id, wf_name)
            return self._doc(turn, outcome="blocked")

        while True:
            metrics.inc("workflow_replays_total", workflow=wf_name)
            try:
                kind, payload, ctx = self._replay(turn.actor_id, wf_name,
                                                  state, orchestrator)
            except WorkflowNondeterminismError as exc:
                state["history"].append(
                    {"t": "failed", "ts": time.time(), "error": str(exc)})
                self._finalize(turn, "failed", error=str(exc))
                return self._doc(turn, outcome="failed")

            if kind == "done":
                state["history"].append(
                    {"t": "completed", "ts": time.time(), "result": payload})
                self._finalize(turn, "completed", result=payload)
                return self._doc(turn, outcome="completed")

            if kind == "wf_failed":
                return await self._compensate(turn, ctx, payload)

            # suspended: fire due timers first — they only append
            # events, so looping here is cheap and side-effect-free
            pending = [t for t in ctx.tasks
                       if not t.resolved and t.seq is not None]
            now = time.time()
            due = [t for t in pending
                   if t.kind == "timer" and t.fire_at <= now]
            if due:
                for t in sorted(due, key=lambda t: t.seq):
                    state["history"].append(
                        {"t": "timer_fired", "ts": now, "seq": t.seq})
                    self._child_span(name="workflow-timer", status=200,
                                     start=now, duration=0.0,
                                     attrs={"seq": t.seq})
                continue

            runnable = [t for t in pending
                        if t.kind == "activity"][:self.replay_batch]
            if runnable:
                await self._run_batch(turn, ctx, runnable)
                self._touch(turn)
                return self._doc(turn, outcome="running",
                                 children=self._children(state, pending))
            timers = [t for t in pending if t.kind == "timer"]
            if timers:
                # pull the drive reminder forward to the next timer
                # fire — a 200ms durable timer must not wait for the
                # periodic drive cadence to come around
                delta = max(0.0, min(t.fire_at for t in timers) - now)
                turn.set_reminder(DRIVE_REMINDER, delta,
                                  period_seconds=self.drive_period)
            self._touch(turn)
            return self._doc(turn, outcome="blocked",
                             children=self._children(state, pending))

    def _replay(self, instance: str, wf_name: str, state: dict,
                orchestrator: Callable):
        """One replay pass: run the orchestrator against history, up to
        its first unresolved await (or to the end)."""
        ctx = WorkflowContext(instance=instance, workflow=wf_name,
                              history=state["history"],
                              input=state.get("input"))
        coro = orchestrator(ctx, state.get("input"))
        try:
            yielded = coro.send(None)
        except StopIteration as stop:
            return "done", stop.value, ctx
        except ActivityError as exc:
            return "wf_failed", str(exc), ctx
        except WorkflowNondeterminismError:
            raise
        except Exception as exc:  # tasklint: disable=error-taxonomy (orchestrator)
            return "wf_failed", f"{type(exc).__name__}: {exc}", ctx
        if not isinstance(yielded, _WorkflowTask):
            raise WorkflowNondeterminismError(
                f"workflow {wf_name!r} awaited a foreign awaitable "
                f"({type(yielded).__name__}); orchestrators may only await "
                "ctx.* tasks — do I/O inside activities")
        # the coroutine is intentionally abandoned (not closed): replay
        # rebuilds it from scratch next turn, and close() would inject
        # GeneratorExit into orchestrator try/finally blocks mid-flight
        return "suspended", yielded, ctx

    # -- activity execution ------------------------------------------------

    async def _run_batch(self, turn: Any, ctx: WorkflowContext,
                         runnable: list[_WorkflowTask]) -> None:
        """Execute up to one batch of activities concurrently; append
        their outcome events and stage their effects onto this turn —
        one commit for the whole batch."""
        outcomes = await asyncio.gather(
            *(self._run_activity(ctx, t.name, t.payload, seq=t.seq)
              for t in runnable))
        now = time.time()
        for task, (ok, value, effects) in zip(runnable, outcomes):
            if ok:
                turn.state["history"].append({
                    "t": "activity_completed", "ts": now,
                    "seq": task.seq, "name": task.name, "result": value})
            else:
                turn.state["history"].append({
                    "t": "activity_failed", "ts": now,
                    "seq": task.seq, "name": task.name, "error": value})
            turn.effects.extend(effects)

    async def _run_activity(self, ctx: WorkflowContext, name: str,
                            input: Any, *, seq: int,
                            is_compensation: bool = False):
        """One activity to completion under its retry policy. Never
        raises (outcomes are data the scheduler records) — except
        :class:`_WorkflowCrashed`, which must abort the whole turn."""
        entry = self.activities.get(name)
        if entry is None:
            metrics.inc("workflow_activity_total", activity=name,
                        status="unregistered")
            return (False, f"no activity named {name!r} is registered", [])
        fn, retry, timeout = entry
        policy = (self.chaos.for_workflow(ctx.workflow, name)
                  if self.chaos is not None else None)
        delays = retry.delays()
        attempt = 0
        while True:
            attempt += 1
            actx = ActivityContext(
                instance=ctx.instance, workflow=ctx.workflow, name=name,
                seq=seq, attempt=attempt, is_compensation=is_compensation)
            span_name = (f"workflow-compensation {name}" if is_compensation
                         else f"workflow-activity {name}")
            wall = time.time()
            started = time.perf_counter()
            try:
                if policy is not None:
                    # the fault fires on the OWNING replica, inside the
                    # activity attempt — so a crashEveryN rule on
                    # workflows.<wf>/<activity> deterministically fells
                    # whoever is executing that step right now
                    try:
                        status = await policy.before_call()
                    except BaseException as exc:
                        if self.crash_on_chaos and self.crash_hook is not None:
                            self.crash_hook()
                            raise _WorkflowCrashed(
                                f"chaos crash inside activity {name!r} "
                                f"(instance {ctx.instance})") from exc
                        raise
                    if status is not None:
                        policy.raise_for_status(status)
                result = await asyncio.wait_for(fn(actx, input),
                                                timeout=timeout)
            except _WorkflowCrashed:
                raise
            except Exception as exc:  # tasklint: disable=error-taxonomy (activity)
                error = f"{type(exc).__name__}: {exc}"
                self._child_span(
                    name=span_name, status=500, start=wall,
                    duration=time.perf_counter() - started,
                    attrs={"activity": name, "attempt": attempt, "seq": seq,
                           "error": error})
                try:
                    delay = next(delays)
                except StopIteration:
                    metrics.inc("workflow_activity_total", activity=name,
                                status="error")
                    logger.warning(
                        "activity %s (instance %s, attempt %d) exhausted "
                        "retries: %s", name, ctx.instance, attempt, error)
                    return (False, error, [])
                metrics.inc("workflow_activity_total", activity=name,
                            status="retry")
                await asyncio.sleep(delay)
                continue
            elapsed = time.perf_counter() - started
            # observed inside the turn's trace scope, so a slow attempt
            # captures the instance trace_id as its exemplar
            metrics.observe("workflow_activity_latency_seconds",
                            elapsed, activity=name)
            metrics.inc("workflow_activity_total", activity=name, status="ok")
            self._child_span(
                name=span_name, status=200, start=wall, duration=elapsed,
                attrs={"activity": name, "attempt": attempt, "seq": seq})
            return (True, result, actx.effects)

    # -- sagas -------------------------------------------------------------

    async def _compensate(self, turn: Any, ctx: WorkflowContext,
                          error: str) -> dict:
        """The orchestrator failed: run registered compensations in
        reverse registration order. Each completed compensation appends
        a ``compensated`` event — replay skips it forever after, which
        is the exactly-once half; reverse order falls out of walking
        the (replay-stable) registration list backwards."""
        state = turn.state
        done = {int(e["idx"]) for e in state["history"]
                if e.get("t") == "compensated"}
        remaining = [i for i in range(len(ctx.compensations) - 1, -1, -1)
                     if i not in done]
        ran = 0
        for idx in remaining:
            if ran >= self.replay_batch:
                # bound the commit like a normal turn; the next drive
                # turn replays, fails at the same point, and continues
                self._touch(turn)
                return self._doc(turn, outcome="running")
            name, cinput = ctx.compensations[idx]
            ok, value, effects = await self._run_activity(
                ctx, name, cinput, seq=-(idx + 1), is_compensation=True)
            event = {"t": "compensated", "ts": time.time(), "idx": idx,
                     "name": name}
            if not ok:
                # a compensation out of retries is recorded (with its
                # error) rather than wedging the saga forever — the
                # history keeps the evidence for the operator
                event["error"] = value
            state["history"].append(event)
            turn.effects.extend(effects)
            metrics.inc("workflow_compensation_total", workflow=state["wf"])
            ran += 1
        state["history"].append(
            {"t": "failed", "ts": time.time(), "error": error})
        self._finalize(turn, "failed", error=error)
        return self._doc(turn, outcome="failed")

    # -- terminal & docs ---------------------------------------------------

    def _finalize(self, turn: Any, status: str, *, result: Any = None,
                  error: str | None = None) -> None:
        state = turn.state
        state["status"] = status
        state["result"] = result
        state["error"] = error
        self._touch(turn)
        turn.clear_reminder(DRIVE_REMINDER)
        if self.retain_seconds > 0:
            turn.set_reminder(GC_REMINDER, self.retain_seconds)
        metrics.inc("workflow_completed_total", workflow=state["wf"],
                    status=status)

    def _touch(self, turn: Any) -> None:
        turn.state["updated"] = time.time()
        metrics.observe("workflow_history_events",
                        len(turn.state.get("history") or ()),
                        workflow=turn.state.get("wf") or "")

    @staticmethod
    def _outcome_of(state: dict) -> str:
        status = state.get("status")
        return status if status in _TERMINAL else "running"

    def _children(self, state: dict,
                  pending: list[_WorkflowTask]) -> tuple[list, list]:
        """(start_children, pending_children) for the result doc. Both
        are recomputed every turn — starts are idempotent on the child,
        and the pending list lets the pump reconcile a lost completion
        notification by polling the child's terminal state."""
        start, waiting = [], []
        for t in pending:
            if t.kind != "child":
                continue
            child_instance = t.payload["instance"]
            event = f"{CHILD_EVENT_PREFIX}{t.seq}"
            start.append({
                "instance": child_instance, "wf": t.name,
                "input": t.payload.get("input"),
                "parent": {"instance": None, "event": event},
            })
            waiting.append({"instance": child_instance, "event": event})
        return start, waiting

    def _doc(self, turn: Any, *, outcome: str,
             children: tuple[list, list] | None = None) -> dict:
        state = turn.state
        start_children, pending_children = children or ([], [])
        for child in start_children:
            # the parent pointer needs OUR instance id, known here
            child["parent"]["instance"] = turn.actor_id
        doc: dict[str, Any] = {
            "instance": turn.actor_id,
            "workflow": state.get("wf"),
            "status": state.get("status"),
            "outcome": outcome,
            "result": state.get("result"),
            "error": state.get("error"),
            "events": len(state.get("history") or ()),
        }
        if start_children:
            doc["start_children"] = start_children
        if pending_children:
            doc["pending_children"] = pending_children
        parent = state.get("parent")
        if outcome in _TERMINAL and parent and parent.get("instance"):
            doc["notify_parent"] = {
                "instance": parent["instance"],
                "event": parent["event"],
                "data": ({"error": state.get("error")}
                         if outcome in ("failed", "terminated")
                         else {"result": state.get("result")}),
                "id": f"{turn.actor_id}::done",
            }
        return doc
