"""The runtime core — every building-block operation, transport-neutral.

This is the sidecar's brain: one ``Runtime`` per app identity, holding
that app's scoped ``ComponentRegistry`` and a channel to the app
itself. The HTTP sidecar (tasksrunner/sidecar.py) adapts it onto
Dapr-shaped routes; the in-process client drives it directly. Keeping
one implementation behind both transports is what makes the two modes
behaviorally identical (SURVEY.md §7.4 "sidecar process model").

Capabilities and their reference anchors:

* state CRUD/query with {app-id}||{key} prefixing —
  Services/TasksStoreManager.cs, docs module 4;
* pub/sub publish with CloudEvents wrap + consumer delivery with
  at-least-once ack — docs module 5, Processor Program.cs:29-33;
* input bindings (cron/queue) delivered to app routes, output bindings
  invoked by name — docs modules 6-7;
* service invocation by app-id through peer sidecars — docs module 3;
* secret reads — docs module 9 / SURVEY.md §5.6;
* trace propagation on every hop — SURVEY.md §5.1.
"""

from __future__ import annotations

import abc
import asyncio
import json
import logging
import os
import time
from typing import Any

from tasksrunner import cloudevents
from tasksrunner.app import App
from tasksrunner.bindings.base import BindingEvent, InputBinding, OutputBinding
from tasksrunner.component.registry import ComponentRegistry
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.errors import (
    ActorError,
    AppNotFound,
    BindingError,
    ComponentNotFound,
    InvocationError,
    StateError,
)
from tasksrunner.invoke.resolver import NameResolver
from tasksrunner.observability.metrics import metrics
from tasksrunner.observability.spans import record_span
from tasksrunner.observability.tracing import (
    BAGGAGE_HEADER,
    TRACEPARENT_HEADER,
    current_or_new,
    ensure_trace,
    serialize_baggage,
    trace_scope,
)
from tasksrunner.pubsub.base import (
    Message, Nack, PubSubBroker, retry_after_from_headers,
)
from tasksrunner.resiliency.policy import ResiliencyPolicies
from tasksrunner.security import TOKEN_ENV, TOKEN_HEADER, AppGrants
from tasksrunner.state.base import StateStore, TransactionOp
from tasksrunner.state.keyprefix import KeyPrefixer

logger = logging.getLogger(__name__)


def _delivery_logs() -> bool:
    """Per-message delivery log lines honor the access-log knob
    (TASKSRUNNER_ACCESS_LOG=0 — see hosting._access_log): both exist
    to keep per-request log formatting off the tuned hot path."""
    from tasksrunner.envflag import env_flag

    return env_flag("TASKSRUNNER_ACCESS_LOG")


class AppChannel(abc.ABC):
    """How the runtime reaches its application."""

    @abc.abstractmethod
    async def request(self, method: str, path: str, *, query: str = "",
                      headers: dict[str, str] | None = None,
                      body: bytes = b"") -> tuple[int, dict[str, str], bytes]: ...

    async def close(self) -> None:  # pragma: no cover - default no-op
        pass


class InProcAppChannel(AppChannel):
    """Direct dispatch into an ``App`` object (test / single-process mode)."""

    def __init__(self, app: App):
        self.app = app

    async def request(self, method, path, *, query="", headers=None, body=b""):
        resp = await self.app.handle(method, path, query=query,
                                     headers=headers, body=body)
        return resp.encode()


class HTTPAppChannel(AppChannel):
    """HTTP dispatch to the app process (sidecar mode)."""

    def __init__(self, host: str, port: int):
        self.base = f"http://{host}:{port}"
        self._session = None

    async def _ensure_session(self):
        if self._session is None:
            import aiohttp
            self._session = aiohttp.ClientSession()
        return self._session

    async def request(self, method, path, *, query="", headers=None, body=b""):
        session = await self._ensure_session()
        url = self.base + path + (f"?{query}" if query else "")
        try:
            async with session.request(method, url, headers=headers or {},
                                       data=body) as resp:
                return resp.status, dict(resp.headers), await resp.read()
        except OSError as exc:
            raise InvocationError(f"app unreachable at {url}: {exc}") from exc

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


class Runtime:
    def __init__(
        self,
        app_id: str | None,
        registry: ComponentRegistry,
        *,
        resolver: NameResolver | None = None,
        app_channel: AppChannel | None = None,
        invoke_retries: int = 3,
        invoke_retry_delay: float = 0.2,
        resiliency: ResiliencyPolicies | None = None,
        grants: "AppGrants | None" = None,
        chaos: Any = None,
    ):
        self.app_id = app_id
        self.registry = registry
        self.resolver = resolver or NameResolver()
        #: connection-level retry policy for peer invocation (≙ the
        #: Dapr sidecar's built-in service-invocation retries,
        #: docs/aca/03-aca-dapr-integration/index.md:30-38). Only
        #: transport failures retry — HTTP error statuses are returned
        #: to the caller untouched.
        self.invoke_retries = max(1, invoke_retries)
        self.invoke_retry_delay = invoke_retry_delay
        #: declarative policies (timeouts/retries/circuit breakers) —
        #: when a target has one it replaces the builtin retry loop
        #: (tasksrunner/resiliency, ≙ Dapr 1.14 kind: Resiliency)
        self.resiliency = resiliency
        #: per-app component authorization (≙ the reference's
        #: least-privilege role assignments, SURVEY.md §5.10); None =
        #: unrestricted. Enforced HERE, transport-neutrally, so the
        #: HTTP sidecar and the in-proc client behave identically.
        self.grants = grants
        #: ChaosPolicies when fault injection is active; the invoke
        #: client consults app-targeted rules per attempt so resiliency
        #: policies (timeout/retry/breaker) see injected faults exactly
        #: like real peer failures. None on the production path.
        self.chaos = chaos
        self.app_channel = app_channel
        #: in-process peer channels (app-id → AppChannel); consulted
        #: before name resolution so a single-process cluster can route
        #: invokes without HTTP (must stay behaviorally identical to the
        #: sidecar path — same headers, same status mapping)
        self.peers: dict[str, AppChannel] = {}
        self._subscriptions = []
        self._input_bindings: list[InputBinding] = []
        self._session = None  # outbound aiohttp session for peer invokes
        self._mesh_pool = None  # outbound framed-mesh connections (invoke/mesh.py)
        from tasksrunner.envflag import env_flag
        self._mesh_enabled = env_flag("TASKSRUNNER_MESH")
        self._started = False
        #: ActorRuntime when TASKSRUNNER_ACTORS is on AND the app
        #: registered @app.actor handlers; None otherwise — the
        #: gate-off path pays one attribute check, nothing more
        self.actors = None
        #: (host, sidecar_port) advertised in actor placement records
        #: so peer replicas can forward turns here; set by
        #: Sidecar.start() before it calls runtime.start()
        self.actor_address: tuple[str, int] | None = None
        #: WorkflowRuntime when TASKSRUNNER_WORKFLOWS is on and the app
        #: hosts the workflow actor type; None otherwise
        self.workflows = None
        #: drill switch forwarded to ActorRuntime (chaos failover test)
        self._actor_crash_on_chaos = False
        # cached metrics.recorder() closures for the per-request latency
        # histograms, keyed by the one label that varies per call — a
        # recorder observation is a float compare + list append, so the
        # hot paths skip the per-call label packing of metrics.observe()
        self._rec_state_save: dict[str, Any] = {}
        self._rec_state_get: dict[str, Any] = {}
        self._rec_state_transact: dict[str, Any] = {}
        self._rec_publish: dict[tuple[str, str], Any] = {}
        self._rec_invoke: dict[str, Any] = {}

    # -- helpers ---------------------------------------------------------

    async def _guarded(self, component_name: str, fn,
                       retriable: tuple[type[BaseException], ...] = (OSError,)):
        """Apply the component's outbound resiliency policy (if any)."""
        if self.resiliency is None:
            return await fn()
        policy = self.resiliency.for_component(component_name)
        if policy is None:
            return await fn()
        return await policy.execute(fn, retriable=retriable)

    def _authorize(self, component: str, op: str, *,
                   topic: str | None = None) -> None:
        if self.grants is not None:
            self.grants.check(component, op, topic=topic, app_id=self.app_id)

    def _state_store(self, name: str) -> tuple[StateStore, KeyPrefixer]:
        store = self.registry.get(name, block="state")
        spec: ComponentSpec = self.registry.spec(name)
        raw = spec.metadata.get("keyPrefix")
        strategy = raw if isinstance(raw, str) else "appid"
        prefixer = KeyPrefixer(strategy, app_id=self.app_id, component_name=name)
        return store, prefixer

    def check_placement_epoch(self, store_name: str,
                              epoch: int | None) -> None:
        """Validate a caller's routing epoch against the store's live
        placement map (elastic placement, PR 20). Stores without a map
        (unsharded engines) and callers without the header pass — only
        a sharded store + an explicit epoch can 409-redirect."""
        if epoch is None:
            return
        store = self.registry.get(store_name, block="state")
        check = getattr(store, "check_epoch", None)
        if check is not None:
            check(epoch)

    # -- state -----------------------------------------------------------

    async def save_state(self, store_name: str, items: list[dict]) -> None:
        self._authorize(store_name, "write")
        store, prefixer = self._state_store(store_name)
        for item in items:
            if "key" not in item:
                raise StateError("each state item needs a key")

        # guard per item, not per batch: a retry must re-run only the
        # failing write — re-running completed etag-guarded items would
        # turn a transient blip into a spurious 409 conflict
        started = time.perf_counter()
        for item in items:
            key = prefixer.apply(str(item["key"]))
            await self._guarded(
                store_name,
                lambda k=key, it=item: store.set(k, it.get("value"),
                                                 etag=it.get("etag")))
        metrics.inc("state_save", len(items), store=store_name)
        rec = self._rec_state_save.get(store_name)
        if rec is None:
            rec = self._rec_state_save[store_name] = metrics.recorder(
                "state_op_latency_seconds", store=store_name, op="save")
        rec(time.perf_counter() - started)

    async def get_state(self, store_name: str, key: str):
        self._authorize(store_name, "read")
        store, prefixer = self._state_store(store_name)
        metrics.inc("state_get", store=store_name)
        started = time.perf_counter()
        item = await self._guarded(
            store_name, lambda: store.get(prefixer.apply(key)))
        rec = self._rec_state_get.get(store_name)
        if rec is None:
            rec = self._rec_state_get[store_name] = metrics.recorder(
                "state_op_latency_seconds", store=store_name, op="get")
        rec(time.perf_counter() - started)
        return item

    async def save_state_item(self, store_name: str, key: str, value: Any, *,
                              etag: str | None = None) -> str:
        """Single-item save that RETURNS the store's new etag.

        ``save_state`` discards etags (the Dapr bulk API has nowhere to
        put them), but the actor runtime's commit chain needs each
        write's resulting etag to guard the next one — re-reading after
        the write would race a newer owner and adopt *their* record.
        Same grants/resiliency/metrics treatment as ``save_state``."""
        self._authorize(store_name, "write")
        store, prefixer = self._state_store(store_name)
        started = time.perf_counter()
        new_etag = await self._guarded(
            store_name,
            lambda: store.set(prefixer.apply(key), value, etag=etag))
        metrics.inc("state_save", store=store_name)
        rec = self._rec_state_save.get(store_name)
        if rec is None:
            rec = self._rec_state_save[store_name] = metrics.recorder(
                "state_op_latency_seconds", store=store_name, op="save")
        rec(time.perf_counter() - started)
        return new_etag

    async def delete_state(self, store_name: str, key: str, *, etag=None) -> bool:
        self._authorize(store_name, "write")
        store, prefixer = self._state_store(store_name)
        metrics.inc("state_delete", store=store_name)
        return await self._guarded(
            store_name, lambda: store.delete(prefixer.apply(key), etag=etag))

    async def bulk_get_state(self, store_name: str, keys: list[str]) -> list[dict]:
        """≙ Dapr's POST /v1.0/state/{store}/bulk."""
        self._authorize(store_name, "read")
        store, prefixer = self._state_store(store_name)
        items = await self._guarded(
            store_name,
            lambda: store.bulk_get([prefixer.apply(str(k)) for k in keys]))
        metrics.inc("state_bulk_get", len(keys), store=store_name)
        out = []
        for key, item in zip(keys, items):
            entry: dict = {"key": str(key)}
            if item is not None:
                entry["data"] = item.value
                entry["etag"] = item.etag
            out.append(entry)
        return out

    async def query_state(self, store_name: str, query: dict) -> dict:
        self._authorize(store_name, "read")
        store, prefixer = self._state_store(store_name)
        resp = await self._guarded(
            store_name, lambda: store.query(query, key_prefix=prefixer.prefix))
        metrics.inc("state_query", store=store_name)
        return {
            "results": [
                {"key": prefixer.strip(i.key), "data": i.value, "etag": i.etag}
                for i in resp.items
            ],
            "token": resp.token,
        }

    async def transact_state(self, store_name: str, operations: list[dict]) -> None:
        self._authorize(store_name, "write")
        store, prefixer = self._state_store(store_name)
        ops = []
        for op in operations:
            kind = op.get("operation")
            if kind not in ("upsert", "delete"):
                raise StateError(f"unknown transaction operation {kind!r}")
            req = op.get("request") or {}
            if "key" not in req:
                raise StateError("each transaction request needs a key")
            ops.append(TransactionOp(
                operation=kind, key=prefixer.apply(str(req["key"])),
                value=req.get("value"), etag=req.get("etag"),
            ))
        # a transaction is atomic in the store, so whole-call retry is
        # safe (unlike the per-item save loop above)
        started = time.perf_counter()
        await self._guarded(store_name, lambda: store.transact(ops))
        metrics.inc("state_transact", store=store_name)
        rec = self._rec_state_transact.get(store_name)
        if rec is None:
            rec = self._rec_state_transact[store_name] = metrics.recorder(
                "state_op_latency_seconds", store=store_name, op="transact")
        rec(time.perf_counter() - started)

    # -- actors ----------------------------------------------------------

    def _actor_runtime(self):
        if self.actors is None:
            raise ActorError(
                "virtual actors are disabled: set TASKSRUNNER_ACTORS=1 and "
                "register at least one @app.actor handler")
        return self.actors

    async def invoke_actor(self, actor_type: str, actor_id: str, method: str,
                           data: Any = None, *, forwarded: bool = False) -> Any:
        return await self._actor_runtime().invoke_turn(
            actor_type, actor_id, method, data, forwarded=forwarded)

    async def register_actor_reminder(
            self, actor_type: str, actor_id: str, name: str, *,
            due_seconds: float, period_seconds: float | None = None,
            data: Any = None, forwarded: bool = False) -> None:
        await self._actor_runtime().register_reminder(
            actor_type, actor_id, name, due_seconds=due_seconds,
            period_seconds=period_seconds, data=data, forwarded=forwarded)

    async def unregister_actor_reminder(self, actor_type: str, actor_id: str,
                                        name: str, *,
                                        forwarded: bool = False) -> None:
        await self._actor_runtime().unregister_reminder(
            actor_type, actor_id, name, forwarded=forwarded)

    async def get_actor_state(self, actor_type: str, actor_id: str) -> dict:
        return await self._actor_runtime().read_state(actor_type, actor_id)

    # -- secrets ---------------------------------------------------------

    def get_secret(self, store_name: str, key: str) -> dict[str, str]:
        self._authorize(store_name, "read")
        store = self.registry.get(store_name, block="secretstores")
        return {key: store.get(key)}

    def bulk_secrets(self, store_name: str) -> dict[str, str]:
        self._authorize(store_name, "read")
        store = self.registry.get(store_name, block="secretstores")
        return store.bulk()

    # -- pub/sub ---------------------------------------------------------

    async def publish(self, pubsub_name: str, topic: str, data: Any, *,
                      metadata: dict[str, str] | None = None,
                      raw: bool = False) -> str:
        self._authorize(pubsub_name, "publish", topic=topic)
        broker: PubSubBroker = self.registry.get(pubsub_name, block="pubsub")
        envelope = data if raw else cloudevents.wrap(
            data, source=self.app_id or "tasksrunner", topic=topic,
            pubsub_name=pubsub_name,
        )
        meta = dict(metadata or {})
        # record the envelope type so delivery presents the right
        # content-type (raw payloads must NOT be unwrapped downstream)
        meta["content-type"] = (
            "application/json" if raw else cloudevents.CONTENT_TYPE)
        # one child context serves as both the wire parent for consumers
        # and the recorded producer span, so the trace tree connects
        ctx = current_or_new()
        child = ctx.child()
        meta[TRACEPARENT_HEADER] = child.header
        bag = serialize_baggage(child.baggage)
        if bag:
            meta[BAGGAGE_HEADER] = bag
        started = time.time()
        msg_id = await self._guarded(
            pubsub_name, lambda: broker.publish(topic, envelope, metadata=meta))
        metrics.inc("publish", pubsub=pubsub_name, topic=topic)
        rec = self._rec_publish.get((pubsub_name, topic))
        if rec is None:
            rec = self._rec_publish[(pubsub_name, topic)] = metrics.recorder(
                "publish_latency_seconds", pubsub=pubsub_name, topic=topic)
        rec(time.time() - started)
        record_span(kind="producer", name=f"publish {pubsub_name}/{topic}",
                    status=200, start=started, duration=time.time() - started,
                    attrs={"target": f"{pubsub_name}/{topic}"},
                    span_id=child.span_id, parent_id=ctx.span_id)
        return msg_id

    # -- bindings --------------------------------------------------------

    async def invoke_output_binding(self, name: str, operation: str, data: Any,
                                    metadata: dict[str, str] | None = None):
        self._authorize(name, "invoke")
        binding = self.registry.get(name, block="bindings")
        if not isinstance(binding, OutputBinding):
            raise BindingError(f"component {name!r} is not an output binding")
        metrics.inc("binding_invoke", binding=name, operation=operation)
        started = time.perf_counter()
        result = await self._guarded(
            name, lambda: binding.invoke(operation, data, metadata))
        metrics.observe("binding_latency_seconds",
                        time.perf_counter() - started,
                        binding=name, operation=operation)
        return result

    # -- service invocation ----------------------------------------------

    async def invoke(self, target_app_id: str, method_path: str, *,
                     http_method: str = "POST", query: str = "",
                     headers: dict[str, str] | None = None,
                     body: bytes = b"") -> tuple[int, dict[str, str], bytes]:
        headers = {k.lower(): v for k, v in (headers or {}).items()}
        incoming = headers.get(TRACEPARENT_HEADER)
        if incoming:
            # caller supplied an explicit trace context: continue it
            base_ctx = ensure_trace(incoming, headers.get(BAGGAGE_HEADER))
        else:
            base_ctx = current_or_new()
        # one child context is both the wire header and the client span
        child = base_ctx.child()
        headers[TRACEPARENT_HEADER] = child.header
        bag = serialize_baggage(child.baggage)
        if bag:
            headers[BAGGAGE_HEADER] = bag
        path = "/" + method_path.lstrip("/")
        metrics.inc("invoke", target=target_app_id)

        started = time.time()

        def _spanned(result: tuple[int, dict[str, str], bytes]):
            elapsed = time.time() - started
            rec = self._rec_invoke.get(target_app_id)
            if rec is None:
                rec = self._rec_invoke[target_app_id] = metrics.recorder(
                    "invoke_latency_seconds", target=target_app_id)
            rec(elapsed)
            record_span(kind="client", name=f"invoke {target_app_id}{path}",
                        status=result[0], start=started,
                        duration=elapsed,
                        attrs={"target": target_app_id},
                        span_id=child.span_id, parent_id=base_ctx.span_id)
            return result

        # chaos rules targeting this app run per ATTEMPT (inside the
        # resiliency policy), so injected faults hit the same retry/
        # breaker/timeout machinery a real flaky peer would exercise.
        # Status-mode faults synthesize a reply; raising faults look
        # like transport errors.
        cpolicy = (self.chaos.for_app(target_app_id)
                   if self.chaos is not None else None)

        async def _chaos_gate() -> tuple[int, dict[str, str], bytes] | None:
            if cpolicy is None:
                return None
            status = await cpolicy.before_call()
            if status is None:
                return None
            return (status, {"x-tasksrunner-chaos": "injected"},
                    json.dumps({"message": "chaos: injected status"}).encode())

        if self.app_id is not None and target_app_id == self.app_id:
            if self.app_channel is None:
                raise InvocationError(f"no app channel for local app {self.app_id!r}")
            injected = await _chaos_gate()
            if injected is not None:
                return _spanned(injected)
            return _spanned(await self.app_channel.request(
                http_method, path, query=query, headers=headers, body=body))

        policy = (self.resiliency.for_app(target_app_id)
                  if self.resiliency is not None else None)

        if target_app_id in self.peers:
            channel = self.peers[target_app_id]

            async def _peer_attempt():
                injected = await _chaos_gate()
                if injected is not None:
                    return injected
                return await channel.request(
                    http_method, path, query=query, headers=headers, body=body)

            if policy is not None:
                try:
                    return _spanned(await policy.execute(
                        _peer_attempt, retriable=(OSError,)))
                except InvocationError:
                    raise
                except (OSError, TimeoutError) as exc:
                    # identical error shape to the sidecar-HTTP branch
                    # below — the two transports must stay behaviorally
                    # interchangeable
                    raise InvocationError(
                        f"cannot reach {target_app_id!r}: {exc}") from exc
            return _spanned(await _peer_attempt())

        token = os.environ.get(TOKEN_ENV)
        if token:
            # peer sidecars in a token-protected cluster share the token
            headers.setdefault(TOKEN_HEADER, token)

        async def _http_attempt(addr):
            if self._session is None:
                import aiohttp
                self._session = aiohttp.ClientSession()
            url = f"{addr.base_url}/v1.0/invoke/{target_app_id}/method{path}"
            if query:
                url += f"?{query}"
            async with self._session.request(http_method, url, headers=headers,
                                             data=body) as resp:
                return resp.status, dict(resp.headers), await resp.read()

        async def _attempt():
            injected = await _chaos_gate()
            if injected is not None:
                return injected
            from tasksrunner.invoke.mesh import MeshConnectError
            from tasksrunner.invoke.pki import mesh_tls_enabled
            # re-resolve each attempt: the peer may have crashed,
            # unregistered, and come back on a new port
            addr = self.resolver.resolve(target_app_id)
            # prefer the framed mesh lane when the peer advertises one
            # (invoke/mesh.py, ≙ Dapr's internal sidecar↔sidecar gRPC);
            # a refused dial falls back to HTTP within this attempt, an
            # in-flight drop raises OSError into the normal retry path
            if mesh_tls_enabled() and not self._mesh_enabled:
                # local misconfiguration, not a peer problem: certs are
                # provisioned but THIS node has the mesh lane switched
                # off. Retrying/re-resolving cannot help — fail fast
                # with an error that points at the right machine.
                raise InvocationError(
                    "mesh_tls: certs are provisioned but the mesh lane "
                    "is disabled on this node (TASKSRUNNER_MESH=0); "
                    "plaintext invokes are refused under mTLS")
            if mesh_tls_enabled() and not addr.mesh_port:
                # a peer with no mesh lane (legacy registration, a
                # TASKSRUNNER_MESH=0 peer, or a tampered registry entry
                # that dropped mesh_port) would route over plaintext
                # HTTP with the token header and no peer identity check
                # — the exact hole the mTLS fence exists to close.
                # Refuse it the same way a failed handshake is refused:
                # retriable, so a re-resolve can land on an honest
                # replica that does advertise the authenticated lane.
                raise MeshConnectError(
                    f"mesh_tls: peer {target_app_id!r} offers no mesh "
                    "lane; refusing plaintext fallback")
            if addr.mesh_port and self._mesh_enabled:
                if self._mesh_pool is None:
                    from tasksrunner.invoke.mesh import MeshPool
                    self._mesh_pool = MeshPool()
                try:
                    result = await self._mesh_pool.request(
                        addr.host, addr.mesh_port, target_app_id,
                        http_method, path, query=query, headers=headers,
                        body=body)
                    metrics.inc("invoke_transport", lane="mesh")
                    return result
                except MeshConnectError:
                    if mesh_tls_enabled():
                        # NO downgrade under mTLS: a failed handshake
                        # (wrong CA, wrong identity, anonymous peer) is
                        # a REFUSAL — falling back to plaintext HTTP
                        # would hand the request, token header and all,
                        # to the very endpoint that just failed to
                        # prove itself. Surface as a retriable
                        # transport error instead (the retry re-resolves
                        # and may reach an honest replica).
                        raise
                    # plaintext mesh: the peer may simply predate the
                    # mesh or have it disabled — HTTP is equivalent
            result = await _http_attempt(addr)
            metrics.inc("invoke_transport", lane="http")
            return result

        if policy is not None:
            # declarative policy replaces the builtin transport retries
            try:
                return _spanned(await policy.execute(
                    _attempt, retriable=(OSError, AppNotFound)))
            except (AppNotFound, InvocationError):
                raise
            except (OSError, TimeoutError) as exc:
                # exhausted budget: surface the same clean error shape
                # as the builtin loop (mapped to HTTP 500, not an
                # unhandled traceback)
                raise InvocationError(
                    f"cannot reach sidecar of {target_app_id!r}: {exc}") from exc

        last_exc: Exception | None = None
        for attempt in range(self.invoke_retries):
            try:
                return _spanned(await _attempt())
            except (OSError, AppNotFound) as exc:
                last_exc = exc
                if attempt + 1 < self.invoke_retries:
                    logger.warning(
                        "invoke %s attempt %d/%d failed (%s); retrying",
                        target_app_id, attempt + 1, self.invoke_retries, exc)
                    await asyncio.sleep(self.invoke_retry_delay * (attempt + 1))
        if isinstance(last_exc, AppNotFound):
            raise last_exc
        raise InvocationError(
            f"cannot reach sidecar of {target_app_id!r} after "
            f"{self.invoke_retries} attempts: {last_exc}"
        ) from last_exc

    # -- consumer-side lifecycle -----------------------------------------

    async def _wait_for_app(self, timeout: float = 30.0) -> None:
        """The subscribe-handshake ordering problem (SURVEY.md §7.4):
        the app may not be listening yet when the sidecar starts."""
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            try:
                # the builtin, non-shadowable liveness path: an app's
                # custom /healthz may report unhealthy until warm, which
                # must not block the subscribe handshake
                status, _, _ = await self.app_channel.request(
                    "GET", "/tasksrunner/healthz")
                if status < 500:
                    return
            # readiness poll: any failure means "not up yet" and is
            # retried until the deadline converts it to InvocationError
            except Exception:  # tasklint: disable=error-taxonomy (poll)
                pass
            if asyncio.get_running_loop().time() > deadline:
                raise InvocationError(
                    f"app {self.app_id!r} did not become healthy within {timeout}s")
            await asyncio.sleep(0.1)

    def _mesh_peers(self) -> list[tuple[str, int, str | None]]:
        """Every mesh address the resolver currently advertises for
        OTHER apps — the keepalive loop's dial list. Under mTLS each
        triple pins the peer's app-id so the pre-warmed connection
        carries the same identity check a request-path dial would."""
        from tasksrunner.invoke.pki import mesh_tls_enabled

        pin_identity = mesh_tls_enabled()
        peers: list[tuple[str, int, str | None]] = []
        for app_id in self.resolver.known_apps():
            if app_id == self.app_id:
                continue
            for addr in self.resolver.resolve_all(app_id):
                if addr.mesh_port:
                    peers.append((addr.host, addr.mesh_port,
                                  app_id if pin_identity else None))
        return peers

    def kick_mesh_prewarm(self) -> None:
        """Wake the mesh keepalive loop now — called right after a
        registration lands so freshly-visible peers are dialed before
        the first ping interval elapses."""
        if self._mesh_pool is not None:
            self._mesh_pool.kick()

    def _start_mesh_prewarm(self) -> None:
        from tasksrunner.invoke.mesh import MeshPool, ping_interval

        if ping_interval() <= 0:
            return
        if self._mesh_pool is None:
            self._mesh_pool = MeshPool()
        self._mesh_pool.start_keepalive(self._mesh_peers)

    async def start(self) -> None:
        """Run the subscribe handshake and start input bindings."""
        if not self._started and self._mesh_enabled and self.app_id:
            # pre-warm routing: dial peers the resolver already knows
            # off the request path, and keep the pool live with idle
            # pings (invoke/mesh.py) — first-request latency then
            # excludes CONNECT_TIMEOUT-class dial cost
            self._start_mesh_prewarm()
        if self._started or self.app_channel is None:
            self._started = True
            return
        # claim the flag before the first suspension: two concurrent
        # start() calls would otherwise both pass the gate and
        # double-subscribe every topic / double-start every binding
        self._started = True
        await self._wait_for_app()

        # 1. topic subscriptions (≙ sidecar GET /dapr/subscribe)
        status, _, body = await self.app_channel.request("GET", "/tasksrunner/subscribe")
        subscriptions = json.loads(body) if status == 200 and body else []
        for sub in subscriptions:
            pubsub_name, topic, route = sub["pubsubname"], sub["topic"], sub["route"]
            try:
                broker = self.registry.get(pubsub_name, block="pubsub")
            except ComponentNotFound:
                # an absent component is skippable (the processor's
                # local-only taskspubsub slot in cloud mode) ...
                logger.warning("app %s subscribes to unknown pubsub %r — skipped",
                               self.app_id, pubsub_name)
                continue
            # ... but an EXISTING one without a subscribe grant fails
            # fast, like a missing "Service Bus Data Receiver" role
            # (processor-backend-service.bicep:190-198): an app must not
            # start silently deaf to a subscription it declared
            self._authorize(pubsub_name, "subscribe", topic=topic)
            handler = self._make_subscription_handler(pubsub_name, route)
            self._subscriptions.append(
                await broker.subscribe(topic, self.app_id or "default", handler))
            logger.info("subscribed %s to %s/%s -> %s",
                        self.app_id, pubsub_name, topic, route)

        # 2. input bindings scoped to this app
        for name in self.registry.names(block="bindings"):
            instance = self.registry.get(name)
            if isinstance(instance, InputBinding):
                if instance.running:
                    # shared instance already started by another runtime
                    # (InProcCluster); a second start would orphan the
                    # first poll task
                    continue
                await instance.start(self._make_binding_sink(instance))
                instance.running = True
                self._input_bindings.append(instance)
                logger.info("input binding %s -> %s", name, instance.route)

        # 3. virtual actors (gated; the off path costs one env read).
        # Workflows ride the actor substrate, so the workflow gate also
        # boots actors — a workflow app need not set both flags.
        from tasksrunner.envflag import env_flag
        if (env_flag("TASKSRUNNER_ACTORS", default=False)
                or env_flag("TASKSRUNNER_WORKFLOWS", default=False)):
            await self._start_actors()

    async def _start_actors(self) -> None:
        """Ask the app which actor types it hosts (≙ the Dapr sidecar's
        GET /dapr/config actor-type discovery) and boot the actor
        runtime when there are any."""
        status, _, body = await self.app_channel.request(
            "GET", "/tasksrunner/actors")
        types = json.loads(body) if status == 200 and body else []
        if not types:
            return
        from tasksrunner.actors import ActorRuntime
        self.actors = ActorRuntime(self, types,
                                   crash_on_chaos=self._actor_crash_on_chaos)
        await self.actors.start()
        await self._start_workflows()

    async def _start_workflows(self) -> None:
        """Attach the workflow runtime when the gate is on and the app
        hosts the workflow actor type (it does as soon as it registered
        one ``@app.workflow``)."""
        from tasksrunner.envflag import env_flag
        from tasksrunner.workflows import WORKFLOW_ACTOR_TYPE, WorkflowRuntime
        if not env_flag("TASKSRUNNER_WORKFLOWS", default=False):
            return
        if self.actors is None or WORKFLOW_ACTOR_TYPE not in self.actors.types:
            return
        self.workflows = WorkflowRuntime(self, self.actors)
        # in-proc apps get the runtime-side wiring pushed into their
        # engine: chaos (so faults can target an activity), the crash
        # hook (so a crash-mode fault fells THIS replica the way
        # SIGKILL would), and the drive cadence (reminder period)
        app = getattr(self.app_channel, "app", None)
        engine = getattr(app, "workflow_engine", None)
        if engine is not None:
            engine.chaos = self.chaos
            engine.crash_on_chaos = self._actor_crash_on_chaos
            engine.crash_hook = self.actors.simulate_crash
            engine.drive_period = self.actors.poll_seconds

    def _inbound_policy(self, component_name: str):
        """The component's inbound resiliency policy (if any) — applied
        on the sidecar→app delivery hop, ≙ Dapr's inbound target
        direction: a transiently-failing handler is retried locally
        before the delivery counts as a nack."""
        if self.resiliency is None:
            return None
        return self.resiliency.for_component(component_name, "inbound")

    def _make_subscription_handler(self, pubsub_name: str, route: str):
        policy = self._inbound_policy(pubsub_name)
        # bound once per subscription: delivery observations are a
        # closure call, no per-message label resolution — and the log
        # knob is read here for the same reason
        record_delivery = metrics.recorder(
            "delivery_latency_seconds", route=route)
        log_deliveries = _delivery_logs()

        async def deliver(msg: Message) -> bool:
            wire_tp = msg.metadata.get(TRACEPARENT_HEADER)
            wire_bag = msg.metadata.get(BAGGAGE_HEADER)
            ctx = ensure_trace(wire_tp, wire_bag)
            with trace_scope(ctx):
                body = json.dumps(msg.data).encode()
                # hand the app the WIRE context, not this loop's child
                # of it: the app makes its own child for the consumer
                # span, and that span must parent directly under the
                # recorded producer span (the loop hop records nothing)
                headers = {
                    "content-type": msg.metadata.get(
                        "content-type", cloudevents.CONTENT_TYPE),
                    TRACEPARENT_HEADER: wire_tp or ctx.header,
                }
                if wire_bag:
                    headers[BAGGAGE_HEADER] = wire_bag

                async def _deliver_once():
                    return await self.app_channel.request(
                        "POST", route, headers=headers, body=body)

                started = time.perf_counter()
                try:
                    if policy is not None:
                        status, resp_headers, _ = await policy.execute(
                            _deliver_once, retriable=(OSError,))
                    else:
                        status, resp_headers, _ = await _deliver_once()
                except Exception:
                    logger.exception("delivery to %s failed", route)
                    return False
                metrics.inc("pubsub_delivery", route=route, status=str(status))
                record_delivery(time.perf_counter() - started)
                # delivery visibility in the multiplexed logs (the
                # sidecar→app hop is an in-process call in host mode,
                # so no access-log line marks it); honors the same
                # knob that silences per-request access-log formatting
                if log_deliveries:
                    logger.info('pubsub delivery "POST %s" %d', route, status)
                if 200 <= status < 300:
                    return True
                if status in (429, 503):
                    # the app declined the delivery without processing
                    # it (admission shed, model warmup) and said when
                    # to come back: honor that instead of hot-looping
                    # the broker's tight retry_delay, and don't charge
                    # the bounded-attempt budget for a message the
                    # handler never looked at
                    delay = retry_after_from_headers(resp_headers)
                    if delay is not None:
                        return Nack(retry_after=delay, counts_attempt=False)
                return False
        return deliver

    def _make_binding_sink(self, binding: InputBinding):
        policy = self._inbound_policy(binding.name)
        record_delivery = metrics.recorder(
            "binding_delivery_latency_seconds", binding=binding.name)

        async def sink(event: BindingEvent) -> bool:
            ctx = ensure_trace(None)
            with trace_scope(ctx):
                body = b"" if event.data is None else json.dumps(event.data).encode()
                headers = {"content-type": "application/json",
                           TRACEPARENT_HEADER: ctx.header}
                headers.update(event.metadata)

                async def _deliver_once():
                    return await self.app_channel.request(
                        "POST", binding.route, headers=headers, body=body)

                started = time.perf_counter()
                try:
                    if policy is not None:
                        status, _, _ = await policy.execute(
                            _deliver_once, retriable=(OSError,))
                    else:
                        status, _, _ = await _deliver_once()
                except Exception:
                    logger.exception("binding delivery to %s failed", binding.route)
                    return False
                metrics.inc("binding_delivery", binding=binding.name,
                            status=str(status))
                record_delivery(time.perf_counter() - started)
                if _delivery_logs():
                    logger.info('binding %s delivery "POST %s" %d',
                                binding.name, binding.route, status)
                return 200 <= status < 300
        return sink

    # -- metadata / teardown ---------------------------------------------

    def metadata(self) -> dict:
        out = {
            "id": self.app_id,
            "components": [
                {"name": n, "type": self.registry.spec(n).type}
                for n in self.registry.names()
            ],
            "subscriptions": [
                {"topic": s.topic, "group": s.group} for s in self._subscriptions
            ],
            "metrics": metrics.snapshot(),
            "histograms": metrics.snapshot_histograms(),
            "metric_kinds": metrics.snapshot_kinds(),
        }
        if self.actors is not None:
            out["actors"] = self.actors.summary()
        if self.workflows is not None:
            out["workflows"] = self.workflows.summary()
        placement = {}
        for n in self.registry.names("state"):
            # metadata() is a read path: report stores that are already
            # built, never instantiate one as a side effect
            instance = self.registry._instances.get(n)
            doc_of = getattr(instance, "placement_doc", None)
            if doc_of is not None:
                placement[n] = doc_of()
        if placement:
            out["placement"] = placement
        return out

    async def stop(self) -> None:
        if self.workflows is not None:
            self.workflows.detach()
            self.workflows = None
        if self.actors is not None:
            await self.actors.stop()
            self.actors = None
        for sub in self._subscriptions:
            await sub.cancel()
        self._subscriptions.clear()
        for binding in self._input_bindings:
            await binding.stop()
            binding.running = False
        self._input_bindings.clear()
        if self._session is not None:
            await self._session.close()
            self._session = None
        if self._mesh_pool is not None:
            await self._mesh_pool.close()
            self._mesh_pool = None
        if self.app_channel is not None:
            await self.app_channel.close()
        await self.registry.close()
        self._started = False
