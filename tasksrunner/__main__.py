from tasksrunner.cli import main

main()
