"""Hosting: run an App with its sidecar, in-process or over HTTP.

Two shapes, behaviorally identical (SURVEY.md §7.4 hard part #1):

* ``AppHost`` — the real thing: the app served on its app-port, a
  sidecar process-mate on its sidecar-port, registration in the shared
  name-resolver file. One AppHost per service process is what the
  orchestrator launches — the analog of one ``dapr run --app-id X
  --app-port P --dapr-http-port D`` terminal
  (snippets/dapr-run-backend-api.md:4-16).
* ``InProcCluster`` — every app + runtime in one event loop with
  direct channels; the integration-test harness (the analog of the
  VS Code compound launcher, .vscode/tasks.json) and the engine for
  fast local dev.
"""

from __future__ import annotations

import asyncio
import logging
import os

from aiohttp import web

from tasksrunner.app import App
from tasksrunner.chaos.engine import ChaosPolicies, chaos_enabled
from tasksrunner.chaos.spec import ChaosSpec, load_chaos
from tasksrunner.client import AppClient
from tasksrunner.component.loader import load_components
from tasksrunner.component.registry import ComponentRegistry
from tasksrunner.component.spec import ComponentSpec
from tasksrunner.invoke.resolver import AppAddress, NameResolver
from tasksrunner.observability.admission import AdmissionController
from tasksrunner.observability.metrics import metrics
from tasksrunner.observability.tracing import (
    TRACEPARENT_HEADER,
    ensure_trace,
    trace_scope,
)
from tasksrunner.resiliency.policy import ResiliencyPolicies
from tasksrunner.resiliency.spec import ResiliencySpec, load_resiliency
from tasksrunner.runtime import InProcAppChannel, Runtime
from tasksrunner.security import AppGrants, grants_from_env
from tasksrunner.sidecar import Sidecar, shed_response

logger = logging.getLogger(__name__)


def _access_log():
    """aiohttp access logger, or None when TASKSRUNNER_ACCESS_LOG=0.
    Returning the default logger keeps aiohttp's stock behavior."""
    from tasksrunner.envflag import env_flag

    if not env_flag("TASKSRUNNER_ACCESS_LOG"):
        return None
    from aiohttp.log import access_logger

    return access_logger


def build_app_server(app: App, admission=None) -> web.Application:
    """aiohttp adapter serving an App over HTTP (the app's own port).

    Tracks request concurrency and serves it at
    ``GET /tasksrunner/stats`` — the measurement source for the
    ``http-concurrency`` autoscale rule (the orchestrator polls each
    replica, the way ACA's HTTP scaler watches concurrent requests,
    docs/aca/09-aca-autoscale-keda/index.md:27-35).

    When an :class:`AdmissionController` is attached and shedding,
    ingress traffic is answered 429 + Retry-After before it reaches the
    app. Exempt: ``/healthz`` (shedding liveness probes would get an
    overloaded replica *restarted*, converting load into an outage) and
    the reserved ``/tasksrunner/*`` namespace (the scaler's stats probe
    must keep measuring exactly when the replica is saturated)."""
    async def dispatch(request: web.Request) -> web.Response:
        if request.method == "GET" and request.path == "/tasksrunner/stats":
            # not counted as load: the scaler's own probe must not
            # inflate the concurrency it measures. Counters live on the
            # App so sidecar-direct dispatch (AppHost) and this server
            # feed the same numbers. The /tasksrunner/ prefix is a
            # reserved namespace (healthz, subscribe, stats) — user
            # routes cannot claim it. When the replica runs with an API
            # token, the probe requires it: an ingress:external app must
            # not leak load numbers to the world (the orchestrator's
            # scaler sends the token).
            import os as _os

            from tasksrunner.security import TOKEN_ENV, TOKEN_HEADER

            required = _os.environ.get(TOKEN_ENV) or None
            if required and request.headers.get(TOKEN_HEADER) != required:
                return web.json_response(
                    {"error": "missing or bad api token"}, status=401)
            return web.json_response(
                {"inflight": app.inflight,
                 "requests_total": app.requests_total})
        if (admission is not None and admission.shedding
                and request.path != "/healthz"
                and not request.path.startswith("/tasksrunner/")):
            metrics.inc("admission_shed_total", route="app")
            return shed_response(admission)
        ctx = ensure_trace(request.headers.get(TRACEPARENT_HEADER))
        with trace_scope(ctx):
            body = await request.read()
            resp = await app.handle(
                request.method, request.path, query=request.query_string,
                headers=dict(request.headers), body=body)
            status, headers, payload = resp.encode()
            return web.Response(status=status, body=payload, headers=headers)

    server = web.Application(client_max_size=16 * 1024 * 1024)
    server.router.add_route("*", "/{path:.*}", dispatch)
    return server


async def _bind_or_explain(site, what: str, host: str, port: int) -> None:
    """TCPSite.start with the one failure every attendee hits mapped to
    a clean error: EADDRINUSE -> PortInUseError naming the port (the
    raw OSError surfaces as a runpy traceback and, under the
    orchestrator, an anonymous crash-loop)."""
    import errno

    from tasksrunner.errors import PortInUseError

    try:
        await site.start()
    except OSError as exc:
        if exc.errno == errno.EADDRINUSE:
            raise PortInUseError(
                f"{what} port {port} on {host} is already in use - "
                f"another replica or a leftover process holds it "
                f"(find it: ss -tlnp | grep {port}); stop it or change "
                f"the configured port") from exc
        raise


class AppHost:
    """App server + sidecar for one service, in one process."""

    def __init__(
        self,
        app: App,
        *,
        components_path: str | None = None,
        specs: list[ComponentSpec] | None = None,
        app_port: int = 0,
        sidecar_port: int = 0,
        host: str = "127.0.0.1",
        bind: str | None = None,
        registry_file: str | None = None,
        resolver: NameResolver | None = None,
        register: bool = True,
        grants: "AppGrants | None" = None,
    ):
        self.app = app
        #: where the sidecar binds and where peers reach this host
        self.host = host
        #: bind address for the app's own server only; "0.0.0.0" =
        #: external ingress. Defaults to ``host`` — overriding it never
        #: moves the sidecar, which stays unexposed (as in ACA).
        self.bind = bind or host
        self.register = register
        self.app_port = app_port
        self.sidecar_port = sidecar_port
        if specs is None:
            specs = load_components(components_path) if components_path else []
        self.specs = specs
        #: Resiliency documents live beside the components (same
        #: resources dir), exactly as Dapr loads them
        self.resiliency_specs: list[ResiliencySpec] = (
            load_resiliency(components_path) if components_path else [])
        #: Chaos documents share the resources dir too, but stay inert
        #: unless the operator runs with TASKSRUNNER_CHAOS=1 — the gate
        #: is checked here once, so a disabled host never even loads them
        self.chaos_specs: list[ChaosSpec] = (
            load_chaos(components_path)
            if components_path and chaos_enabled() else [])
        self.resolver = resolver or NameResolver(registry_file=registry_file)
        #: per-app component authorization; None = unrestricted, or set
        #: TASKSRUNNER_GRANTS (the orchestrator does, per app spec)
        self.grants = grants if grants is not None else grants_from_env()
        self._app_runner: web.AppRunner | None = None
        self.sidecar: Sidecar | None = None
        self.client: AppClient | None = None
        #: one admission controller per replica (None unless
        #: TASKSRUNNER_ADMISSION=1), shared by the app server and the
        #: sidecar so both shed on the same saturation state; it reads
        #: App.inflight as its in-flight signal. The sidecar owns its
        #: start/stop alongside the loop-lag probe.
        self.admission = AdmissionController.from_env(
            inflight=lambda: self.app.inflight)

    async def start(self) -> None:
        # Any failure past the first bind must unwind what already
        # started: a PortInUseError on the SIDECAR port would otherwise
        # leave the APP port silently held by this half-started host,
        # so the operator's retry (or the next replica) hits a second,
        # self-inflicted PortInUseError on a port nothing serves.
        try:
            await self._start_inner()
        except BaseException:
            await self._unwind_start()
            raise

    async def _start_inner(self) -> None:
        # 1. the app's own HTTP server. Access logging is on by default
        # (the workshop reads those lines); TASKSRUNNER_ACCESS_LOG=0
        # disables it — measured at ~2x request throughput on the write
        # path (see BASELINE.md), the first tuning for a hot deployment.
        self._app_runner = web.AppRunner(
            build_app_server(self.app, admission=self.admission),
            access_log=_access_log())
        await self._app_runner.setup()
        site = web.TCPSite(self._app_runner, self.bind, self.app_port)
        await _bind_or_explain(site, "app", self.bind, self.app_port)
        if self.app_port == 0:
            self.app_port = self._app_runner.addresses[0][1]

        # 2. the sidecar beside it. App and sidecar share this process,
        # so sidecar→app dispatch is a direct call — the process
        # boundaries that remain HTTP are exactly the reference's [PB]
        # hops (peer sidecars, other services). The two-process layout
        # (`tasksrunner serve` + `tasksrunner sidecar`) keeps the
        # HTTPAppChannel; both must stay behaviorally identical
        # (SURVEY.md §7.4 hard part #1 — App.handle adopts trace
        # context and feeds the same request counters either way).
        chaos = (ChaosPolicies(self.chaos_specs, app_id=self.app.app_id)
                 if self.chaos_specs else None)
        registry = ComponentRegistry(self.specs, app_id=self.app.app_id,
                                     chaos=chaos)
        runtime = Runtime(
            self.app.app_id, registry, resolver=self.resolver,
            app_channel=InProcAppChannel(self.app),
            resiliency=ResiliencyPolicies(
                self.resiliency_specs, app_id=self.app.app_id)
            if self.resiliency_specs else None,
            grants=self.grants,
            chaos=chaos,
        )
        self.sidecar = Sidecar(runtime, host=self.host, port=self.sidecar_port,
                               admission=self.admission)
        await self.sidecar.start()
        self.sidecar_port = self.sidecar.port

        # 3. register for peer discovery — appended to the app's
        # replica list, so every serving replica is in the invoke
        # rotation — then hand the app its client
        if self.register:
            # off-loop: the registry mutation busy-waits on a lock file
            # (worst case seconds if a crashed holder left it behind)
            # and must not stall this replica's event loop at startup
            await asyncio.to_thread(self.resolver.register, AppAddress(
                app_id=self.app.app_id, host=self.host,
                sidecar_port=self.sidecar_port, app_port=self.app_port,
                mesh_port=self.sidecar.mesh_port,
            ))
            # our registration may have made US visible to peers — and
            # their registrations visible to us: pre-dial them now
            # instead of waiting out the first keepalive interval
            runtime.kick_mesh_prewarm()
        # the app's client talks to its sidecar runtime directly — same
        # process, same Runtime object the HTTP surface serves, same
        # grant/scope enforcement (runtime.py is transport-neutral).
        # Real HTTP starts at the first process boundary: peer invokes.
        self.client = AppClient.direct(runtime)
        self.app.client = self.client
        await self.app.startup()
        logger.info("app %s on :%d, sidecar on :%d",
                    self.app.app_id, self.app_port, self.sidecar_port)

    async def _unwind_start(self) -> None:
        """Tear down whatever a failed start() got through, in reverse
        order, keeping the original exception the caller sees. Each
        step is best-effort: a secondary teardown failure is logged,
        never allowed to mask why startup failed."""
        if self.register:
            try:
                # scoped to this replica; a no-op if registration never
                # happened (unregister filters by pid + sidecar port)
                await asyncio.to_thread(
                    self.resolver.unregister, self.app.app_id,
                    pid=os.getpid(), sidecar_port=self.sidecar_port)
            except Exception:
                logger.exception("start unwind: unregister failed")
        if self.client is not None:
            try:
                await self.client.close()
            except Exception:
                logger.exception("start unwind: client close failed")
            self.client = None
        if self.sidecar is not None:
            try:
                await self.sidecar.stop()
            except Exception:
                logger.exception("start unwind: sidecar stop failed")
            self.sidecar = None
        if self._app_runner is not None:
            try:
                await self._app_runner.cleanup()
            except Exception:
                logger.exception("start unwind: app runner cleanup failed")
            self._app_runner = None

    async def stop(self) -> None:
        await self.app.shutdown()
        if self.register:
            # scoped to THIS replica's entry: a stopping replica must
            # not deregister its siblings; off-loop for the same
            # lock-file busy-wait reason as register above
            await asyncio.to_thread(
                self.resolver.unregister, self.app.app_id, pid=os.getpid(),
                sidecar_port=self.sidecar_port)
        if self.client is not None:
            await self.client.close()
        if self.sidecar is not None:
            await self.sidecar.stop()
        if self._app_runner is not None:
            await self._app_runner.cleanup()
            self._app_runner = None


class InProcCluster:
    """N apps + N runtimes in one event loop, no sockets.

    Each app still gets its *own* scoped component registry and its own
    runtime — only the transport differs from production.
    """

    def __init__(self, specs: list[ComponentSpec] | None = None, *,
                 resiliency_specs: list[ResiliencySpec] | None = None,
                 chaos_specs: list[ChaosSpec] | None = None,
                 grants: dict[str, AppGrants | dict] | None = None):
        self.specs = specs or []
        self.resiliency_specs = resiliency_specs or []
        #: one ChaosPolicies for the whole cluster (component instances
        #: are shared across apps, so their wrappers must be too);
        #: still behind the TASKSRUNNER_CHAOS gate
        self.chaos = (
            ChaosPolicies(chaos_specs)
            if chaos_specs and chaos_enabled() else None)
        #: optional per-app grants (app_id → AppGrants or raw mapping);
        #: apps absent from the dict run unrestricted
        self.grants = {
            app_id: g if isinstance(g, AppGrants)
            else AppGrants.parse(g, app_id=app_id)
            for app_id, g in (grants or {}).items()
        }
        self.apps: dict[str, App] = {}
        self.runtimes: dict[str, Runtime] = {}
        self._channels: dict[str, InProcAppChannel] = {}
        #: component instances shared across apps by name (a broker
        #: must be one object for publisher and subscriber in-proc)
        self._shared_instances: dict[str, object] = {}

    def add_app(self, app: App) -> None:
        self.apps[app.app_id] = app

    def _make_registry(self, app_id: str) -> ComponentRegistry:
        reg = ComponentRegistry(self.specs, app_id=app_id, chaos=self.chaos)
        # share instances across apps: first builder wins, others reuse
        original_get = reg.get

        def sharing_get(name: str, *, block: str | None = None):
            if name in self._shared_instances:
                spec = reg.spec(name)  # scope + block checks still apply
                if block is not None and spec.block != block:
                    original_get(name, block=block)  # raises consistently
                reg._instances[name] = self._shared_instances[name]
                return self._shared_instances[name]
            instance = original_get(name, block=block)
            self._shared_instances[name] = instance
            return instance

        reg.get = sharing_get  # type: ignore[method-assign]
        return reg

    async def start(self) -> None:
        for app_id, app in self.apps.items():
            channel = InProcAppChannel(app)
            self._channels[app_id] = channel
            runtime = Runtime(
                app_id, self._make_registry(app_id), app_channel=channel,
                resiliency=ResiliencyPolicies(self.resiliency_specs, app_id=app_id)
                if self.resiliency_specs else None,
                grants=self.grants.get(app_id),
                chaos=self.chaos)
            self.runtimes[app_id] = runtime
            app.client = AppClient.direct(runtime)
        # wire peers after all channels exist
        for app_id, runtime in self.runtimes.items():
            runtime.peers = {
                other: ch for other, ch in self._channels.items() if other != app_id
            }
        for app_id, app in self.apps.items():
            await app.startup()
            await self.runtimes[app_id].start()

    async def stop(self) -> None:
        for app_id, app in self.apps.items():
            await app.shutdown()
        seen: set[int] = set()
        for runtime in self.runtimes.values():
            # shared instances: make sure each closes exactly once
            for name, inst in list(runtime.registry._instances.items()):
                if id(inst) in seen:
                    runtime.registry._instances.pop(name)
                seen.add(id(inst))
            await runtime.stop()

    def client(self, app_id: str) -> AppClient:
        return self.apps[app_id].client
