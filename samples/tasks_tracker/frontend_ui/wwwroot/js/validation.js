// Client-side form validation for the Tasks Tracker frontend.
// ≙ the reference's jquery-validation + unobtrusive bundle
// (wwwroot/lib/, wired in Pages/Shared/_ValidationScriptsPartial.cshtml):
// instant feedback in the browser, with MESSAGES IDENTICAL to the
// server's DataAnnotations analog (app.py `_validate_task_form`) —
// the server remains the authority; this only saves a round trip.
(function () {
  "use strict";

  function message(kind, display) {
    if (kind === "required") return "The " + display + " field is required.";
    if (kind === "email")
      return "The " + display + " field is not a valid e-mail address.";
    return "The " + display + " field must be a valid date.";
  }

  function validateField(input) {
    var display = input.getAttribute("data-display") || input.name;
    var value = (input.value || "").trim();
    if (!value) return message("required", display);
    if (input.type === "email" &&
        (value.indexOf("@") < 0 || value.indexOf(" ") >= 0))
      return message("email", display);
    if (input.type === "date" && isNaN(Date.parse(value)))
      return message("date", display);
    return null;
  }

  function show(input, error) {
    var span = input.parentElement.parentElement
      .querySelector(".field-error[data-for='" + input.name + "']");
    if (!span) {
      span = document.createElement("span");
      span.className = "field-error";
      span.setAttribute("data-for", input.name);
      input.parentElement.insertAdjacentElement("afterend", span);
    }
    span.textContent = error || "";
    input.classList.toggle("input-validation-error", !!error);
  }

  document.addEventListener("submit", function (ev) {
    var form = ev.target;
    if (!form.hasAttribute("data-validate")) return;
    var ok = true;
    form.querySelectorAll("input[data-display]").forEach(function (input) {
      var error = validateField(input);
      show(input, error);
      if (error) ok = false;
    });
    if (!ok) ev.preventDefault();
  });

  // live re-validation once a field has been marked invalid
  document.addEventListener("input", function (ev) {
    var input = ev.target;
    if (input.classList && input.classList.contains("input-validation-error"))
      show(input, validateField(input));
  });
})();
