// Site-wide behaviors (≙ the reference's wwwroot/js/site.js slot).
(function () {
  "use strict";
  // confirm destructive row actions — delete posts immediately, so
  // give the pointer-click path one guard
  document.addEventListener("submit", function (ev) {
    var form = ev.target;
    if (form.matches("form[data-confirm]") &&
        !window.confirm(form.getAttribute("data-confirm")))
      ev.preventDefault();
  });
})();
