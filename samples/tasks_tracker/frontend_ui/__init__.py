from samples.tasks_tracker.frontend_ui.app import make_app

__all__ = ["make_app"]
