"""Frontend web app — app-id ``tasksmanager-frontend-webapp``.

Server-rendered UI ≙ the reference's Razor Pages
(TasksTracker.WebPortal.Frontend.Ui/Pages):

* ``/``                 — email form → ``TasksCreatedByCookie`` →
  redirect to /tasks (Pages/Index.cshtml.cs:23-31)
* ``/tasks``            — list for the cookie user via service
  invocation only, plus complete/delete post handlers
  (Pages/Tasks/Index.cshtml.cs:8-72; invoke at :48)
* ``/tasks/create``     — form → POST api/tasks (Create.cshtml.cs:46)
* ``/tasks/edit/{id}``  — GET task :38 + PUT update :66

Every backend call goes through ``invoke_method`` to app-id
``tasksmanager-backend-api`` — the frontend knows no backend URL
(the whole point of module 3).
"""

from __future__ import annotations

import html
import os
import pathlib
from http.cookies import SimpleCookie
from urllib.parse import urlencode

from tasksrunner import App, Response

APP_ID = "tasksmanager-frontend-webapp"
BACKEND_APP_ID = "tasksmanager-backend-api"
COOKIE_NAME = "TasksCreatedByCookie"  # Pages/Index.cshtml.cs:27


#: (field, display name, input type) ≙ the [Required]/[Display]
#: annotations on TaskAddModel (Pages/Tasks/Models/TasksModel.cs:6-49)
FORM_FIELDS = (
    ("taskName", "Task Name", "text"),
    ("taskDueDate", "Task Due Date", "date"),
    ("taskAssignedTo", "Task Assigned To", "email"),
)


def _validate_task_form(form: dict[str, str]) -> dict[str, str]:
    """Server-side DataAnnotations analog: per-field error messages in
    the reference's wording (client `required` attrs are kept too, but
    the server must not trust them). NORMALIZES in place — the values
    validated here are exactly the values later sent to the backend,
    so nothing can pass validation and still fail server-side."""
    import datetime as dt

    errors: dict[str, str] = {}
    for name, display, kind in FORM_FIELDS:
        value = (form.get(name) or "").strip()
        form[name] = value
        if not value:
            errors[name] = f"The {display} field is required."
        elif kind == "email" and ("@" not in value or " " in value):
            errors[name] = f"The {display} field is not a valid e-mail address."
        elif kind == "date":
            try:
                dt.date.fromisoformat(value)
            except ValueError:
                errors[name] = f"The {display} field must be a valid date."
    return errors


def _date_input_value(raw: str) -> tuple[str, str | None]:
    """A stored datetime → the YYYY-MM-DD a date input needs.

    Parses rather than slices: a malformed stored value must surface
    as a visible field error, not render as a silently clipped
    plausible-looking date (the same honesty the per-field validation
    gives user input)."""
    import datetime as dt

    raw = (raw or "").strip()
    if not raw:
        return "", None
    try:
        return dt.datetime.fromisoformat(raw).date().isoformat(), None
    except ValueError:
        return "", (f"The stored value {raw!r} is not a valid date — "
                    f"please pick the due date again.")


def _task_form_page(title: str, action: str, submit: str,
                    values: dict[str, str],
                    errors: dict[str, str]) -> Response:
    """Render the create/edit form with preserved values and per-field
    validation messages (≙ Razor's asp-validation-for spans). Inputs
    carry data-display so validation.js mirrors the exact server
    messages client-side."""
    errors = dict(errors)
    rows = []
    for name, display, kind in FORM_FIELDS:
        raw = values.get(name) or ""
        if kind == "date":
            value, date_err = _date_input_value(raw)
            if date_err and name not in errors:
                errors[name] = date_err
        else:
            value = raw
        err = (f'<span class="field-error" data-for="{name}">'
               f'{html.escape(errors[name])}</span>'
               if name in errors else "")
        invalid = " input-validation-error" if name in errors else ""
        rows.append(
            f'<p><label>{html.escape(display)} '
            f'<input type="{kind}" name="{name}" value="{html.escape(value)}"'
            f' data-display="{html.escape(display)}"'
            f' class="form-input{invalid}" required>'
            f'</label>{err}</p>')
    body = (f'<h2>{html.escape(title)}</h2>'
            f'<form method="post" action="{html.escape(action)}" data-validate>'
            + "".join(rows)
            + f'<button type="submit">{html.escape(submit)}</button> '
              f'<a href="/tasks">Cancel</a></form>')
    page = _page(title, body)
    if errors:
        page.status = 400  # invalid ModelState re-renders, not redirects
    return page


def _is_unreachable(exc: Exception) -> bool:
    """True when a backend call failed to *connect* (pinned-URL fallback
    refused, name resolution failed, circuit open) rather than the
    backend answering with an error of its own."""
    from tasksrunner.errors import (
        CircuitOpenError,
        InvocationError,
        InvocationStatusError,
    )

    if isinstance(exc, (OSError, CircuitOpenError)):
        # OSError covers aiohttp's ClientConnectorError; an open circuit
        # means the call was never attempted — the backend is down from
        # the reader's point of view
        return True
    try:
        import aiohttp
        # e.g. ServerDisconnectedError: ClientConnectionError but not OSError
        if isinstance(exc, aiohttp.ClientConnectionError):
            return True
    except ImportError:  # pragma: no cover - aiohttp is in the image
        pass
    # every connect-level failure surfaces as InvocationError ("app
    # unreachable at …", "sidecar unreachable at …", AppNotFound);
    # InvocationStatusError is the one that means the backend answered
    return (isinstance(exc, InvocationError)
            and not isinstance(exc, InvocationStatusError))


def _cookie_user(req) -> str | None:
    jar = SimpleCookie(req.headers.get("cookie", ""))
    morsel = jar.get(COOKIE_NAME)
    return morsel.value if morsel else None


def _redirect(location: str, *, set_cookie: str | None = None) -> Response:
    headers = {"location": location}
    if set_cookie is not None:
        headers["set-cookie"] = f"{COOKIE_NAME}={set_cookie}; Path=/; HttpOnly"
    return Response(status=303, headers=headers)


def _page(title: str, body: str) -> Response:
    """Shared layout (≙ Pages/Shared/_Layout.cshtml:1-52): every page
    renders through this one chrome — head with the stylesheet, a
    header with nav, the page body, a footer, and the script includes
    (site behaviors + client-side validation, ≙ the layout's
    jquery/validation bundle from wwwroot/lib). Assets come from the
    wwwroot tree served at /static."""
    doc = f"""<!doctype html>
<html><head><meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>{html.escape(title)} — Tasks Tracker</title>
<link rel="stylesheet" href="/static/css/site.css"></head>
<body>
<header class="site"><a href="/tasks">Tasks Tracker</a>
<span class="sub">{html.escape(title)}</span>
<nav><a href="/tasks">Tasks</a><a href="/tasks/create">New task</a><a href="/">Switch user</a></nav>
</header>
<main><div class="card">{body}</div></main>
<footer class="site">Tasks Tracker — tasksrunner workshop sample</footer>
<script src="/static/js/site.js"></script>
<script src="/static/js/validation.js"></script>
</body></html>"""
    return Response(status=200, body=doc,
                    headers={"content-type": "text/html; charset=utf-8"})


def make_app() -> App:
    app = App(APP_ID)
    # the wwwroot asset tree (≙ UseStaticFiles over wwwroot/)
    app.static("/static", pathlib.Path(__file__).parent / "wwwroot")
    # one reused session for the direct-HTTP fallback path, like the
    # reference's named HttpClient "BackEndApiExternal" (a factory-
    # managed, reused client — Frontend Program.cs:15-27)
    fallback_session: dict[str, object] = {}

    @app.on_startup
    async def _open_fallback_session():
        # eager creation: a lazy check-then-create in the request path
        # would race under concurrent first requests and leak a session
        if os.environ.get("BACKENDAPICONFIG__BASEURLEXTERNALHTTP"):
            import aiohttp
            fallback_session["s"] = aiohttp.ClientSession()

    @app.on_shutdown
    async def _close_fallback_session():
        session = fallback_session.pop("s", None)
        if session is not None:
            await session.close()

    # -- landing page (Pages/Index.cshtml) -------------------------------

    @app.get("/")
    async def index(req):
        return _page("Sign in", """
<p>Enter your email to view and manage your tasks.</p>
<form method="post" action="/">
  <label>Email <input type="email" name="email" required></label>
  <button type="submit">Continue</button>
</form>""")

    @app.post("/")
    async def index_post(req):
        email = _form_field(req, "email")
        if not email:
            return _page("Sign in", "<p>Email is required.</p>")
        return _redirect("/tasks", set_cookie=email)

    # -- task list (Pages/Tasks/Index.cshtml) ----------------------------

    async def _list_tasks(user: str) -> list[dict]:
        """Normally via service invocation. The reference keeps a
        pre-invocation fallback — a named HttpClient configured with
        BackendApiConfig:BaseUrlExternalHttp (Frontend Program.cs:15-27,
        commented alternatives in Pages/Tasks/Index.cshtml.cs:29-45);
        same here: set BACKENDAPICONFIG__BASEURLEXTERNALHTTP to call
        the API's HTTP endpoint directly instead."""
        base = os.environ.get("BACKENDAPICONFIG__BASEURLEXTERNALHTTP")
        if base and "s" in fallback_session:
            session = fallback_session["s"]
            async with session.get(
                f"{base.rstrip('/')}/api/tasks",
                params={"createdBy": user}) as resp:
                resp.raise_for_status()
                return await resp.json()
        return await app.client.invoke_json(
            BACKEND_APP_ID, "api/tasks",
            query=urlencode({"createdBy": user}))

    @app.get("/tasks")
    async def task_list(req):
        user = _cookie_user(req)
        if not user:
            return _redirect("/")
        try:
            tasks = await _list_tasks(user)
        except Exception as exc:
            if not _is_unreachable(exc):
                raise
            # the module-2 lesson made visible: say plainly that the
            # backend could not be reached (pinned-URL readers see this;
            # invoke readers never should, since resolution is per-call).
            # An open circuit keeps its 503 — module 13's fast-fail
            # contract — while a dead backend is a 502 bad-gateway.
            from tasksrunner.errors import CircuitOpenError

            page = _page("Backend unreachable", f"""
<p class="field-error">The backend API is unreachable.</p>
<p>{html.escape(str(exc))}</p>
<p>Check that <code>tasksmanager-backend-api</code> is running, then
<a href="/tasks">reload</a>.</p>""")
            page.status = 503 if isinstance(exc, CircuitOpenError) else 502
            return page
        rows = "".join(_task_row(t) for t in tasks) or \
            '<tr><td colspan="6">No tasks yet.</td></tr>'
        return _page("Tasks", f"""
<p>Signed in as <b>{html.escape(user)}</b> — <a href="/tasks/create">Create new task</a></p>
<table><tr><th>Name</th><th>Due</th><th>Assigned to</th><th>Status</th>
<th></th><th></th></tr>{rows}</table>""")

    def _task_row(t: dict) -> str:
        status = ('<span class="done">completed</span>' if t.get("isCompleted")
                  else '<span class="overdue">overdue</span>' if t.get("isOverDue")
                  else "open")
        tid = html.escape(t.get("taskId", ""))
        return f"""<tr>
<td><a href="/tasks/edit/{tid}">{html.escape(t.get('taskName', ''))}</a></td>
<td>{html.escape(t.get('taskDueDate', ''))}</td>
<td>{html.escape(t.get('taskAssignedTo', ''))}</td>
<td>{status}</td>
<td><form class="inline" method="post" action="/tasks/complete/{tid}">
    <button {'disabled' if t.get('isCompleted') else ''}>Complete</button></form></td>
<td><form class="inline" method="post" action="/tasks/delete/{tid}"
    data-confirm="Delete this task?">
    <button class="danger">Delete</button></form></td></tr>"""

    @app.post("/tasks/complete/{task_id}")
    async def complete(req):
        # ≙ OnPostCompleteAsync (Pages/Tasks/Index.cshtml.cs:65-71)
        await app.client.invoke_method(
            BACKEND_APP_ID, f"api/tasks/{req.path_params['task_id']}/markcomplete",
            http_method="PUT")
        return _redirect("/tasks")

    @app.post("/tasks/delete/{task_id}")
    async def delete(req):
        # ≙ OnPostDeleteAsync (:57-63)
        await app.client.invoke_method(
            BACKEND_APP_ID, f"api/tasks/{req.path_params['task_id']}",
            http_method="DELETE")
        return _redirect("/tasks")

    # -- create (Pages/Tasks/Create.cshtml) ------------------------------

    @app.get("/tasks/create")
    async def create_form(req):
        if not _cookie_user(req):
            return _redirect("/")
        return _task_form_page("Create task", "/tasks/create", "Create",
                               values={}, errors={})

    @app.post("/tasks/create")
    async def create_post(req):
        user = _cookie_user(req)
        if not user:
            return _redirect("/")
        form = _form(req)
        errors = _validate_task_form(form)
        if errors:
            # invalid ModelState: re-render with per-field messages and
            # the user's input preserved (≙ Page() on !ModelState.IsValid)
            return _task_form_page("Create task", "/tasks/create", "Create",
                                   values=form, errors=errors)
        resp = await app.client.invoke_method(
            BACKEND_APP_ID, "api/tasks", http_method="POST",
            data={
                "taskName": form.get("taskName", ""),
                "taskCreatedBy": user,
                "taskDueDate": form.get("taskDueDate", ""),
                "taskAssignedTo": form.get("taskAssignedTo", ""),
            })
        resp.raise_for_status()
        return _redirect("/tasks")

    # -- edit (Pages/Tasks/Edit.cshtml) ----------------------------------

    @app.get("/tasks/edit/{task_id}")
    async def edit_form(req):
        if not _cookie_user(req):
            return _redirect("/")
        tid = req.path_params["task_id"]
        resp = await app.client.invoke_method(
            BACKEND_APP_ID, f"api/tasks/{tid}", http_method="GET")
        if resp.status == 404:
            return Response(status=404, body="task not found")
        t = resp.raise_for_status().json()
        return _task_form_page("Edit task", f"/tasks/edit/{tid}", "Save",
                               values=t, errors={})

    @app.post("/tasks/edit/{task_id}")
    async def edit_post(req):
        if not _cookie_user(req):
            return _redirect("/")
        tid = req.path_params["task_id"]
        form = _form(req)
        errors = _validate_task_form(form)
        if errors:
            return _task_form_page("Edit task", f"/tasks/edit/{tid}", "Save",
                                   values=form, errors=errors)
        resp = await app.client.invoke_method(
            BACKEND_APP_ID, f"api/tasks/{tid}",
            http_method="PUT",
            data={
                "taskName": form.get("taskName", ""),
                "taskDueDate": form.get("taskDueDate", ""),
                "taskAssignedTo": form.get("taskAssignedTo", ""),
            })
        resp.raise_for_status()
        return _redirect("/tasks")

    return app


def _form(req) -> dict[str, str]:
    from urllib.parse import parse_qsl
    return dict(parse_qsl(req.body.decode("utf-8", "replace")))


def _form_field(req, name: str) -> str:
    return _form(req).get(name, "").strip()
