"""Tasks Tracker — the reference's 3-service sample application rebuilt
on the tasksrunner framework.

Service map (SURVEY.md §2.1-2.3):

* ``backend_api``  — app-id ``tasksmanager-backend-api``: REST CRUD +
  state + publish (≙ TasksTracker.TasksManager.Backend.Api)
* ``frontend_ui``  — app-id ``tasksmanager-frontend-webapp``:
  server-rendered UI calling the API only via service invocation
  (≙ TasksTracker.WebPortal.Frontend.Ui)
* ``processor``    — app-id ``tasksmanager-backend-processor``:
  subscriber + cron job + external bindings
  (≙ TasksTracker.Processor.Backend.Svc)

Each service deliberately owns its own copy of the task model, matching
the reference's microservice decoupling (SURVEY.md §2.3 "duplicate DTO
— deliberate").
"""
