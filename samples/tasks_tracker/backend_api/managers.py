"""Tasks-manager service layer: interface + both implementations.

≙ the reference's Services/ directory:

* ``TasksManager``      — ITasksManager.cs:5-15 (8 async ops)
* ``FakeTasksManager``  — FakeTasksManager.cs:5-113 (in-memory, seeds
  10 random tasks at startup; module-1 mode and the test double)
* ``TasksStoreManager`` — TasksStoreManager.cs:9-157 (state store CRUD
  + EQ-filter queries + TaskSaved publish on create :36 and on
  reassign :95-98)
"""

from __future__ import annotations

import abc
import asyncio
import datetime as dt
import logging
import random

from samples.tasks_tracker.backend_api.models import (
    TaskModel,
    add_model,
    apply_update,
    format_dt,
)

logger = logging.getLogger(__name__)

STORE_NAME = "statestore"            # TasksStoreManager.cs:11
PUBSUB_NAME = "dapr-pubsub-servicebus"  # TasksStoreManager.cs:153
TOPIC_NAME = "tasksavedtopic"        # TasksStoreManager.cs:154


class TasksManager(abc.ABC):
    """≙ ITasksManager (Services/ITasksManager.cs:5-15)."""

    @abc.abstractmethod
    async def get_tasks_by_creator(self, created_by: str) -> list[TaskModel]: ...

    @abc.abstractmethod
    async def get_task_by_id(self, task_id: str) -> TaskModel | None: ...

    @abc.abstractmethod
    async def create_new_task(self, add_doc: dict) -> str: ...

    @abc.abstractmethod
    async def update_task(self, task_id: str, update_doc: dict) -> bool: ...

    @abc.abstractmethod
    async def mark_task_completed(self, task_id: str) -> bool: ...

    @abc.abstractmethod
    async def delete_task(self, task_id: str) -> bool: ...

    @abc.abstractmethod
    async def get_yesterdays_due_tasks(self) -> list[TaskModel]: ...

    @abc.abstractmethod
    async def mark_overdue_tasks(self, tasks: list[dict]) -> None: ...


class FakeTasksManager(TasksManager):
    """In-memory implementation seeded with 10 random tasks
    (FakeTasksManager.GenerateRandomTasks, :10-25). Lock-guarded where
    the reference's List<> was not (SURVEY.md §5.2)."""

    def __init__(self, *, seed_count: int = 10):
        self._tasks: dict[str, TaskModel] = {}
        self._lock = asyncio.Lock()
        rng = random.Random(42)
        for i in range(seed_count):
            t = TaskModel(
                task_name=f"Task number: {i}",
                task_created_by="tempuser@mail.com",
                task_due_date=format_dt(
                    dt.datetime.now() + dt.timedelta(days=rng.randint(-5, 5))),
                task_assigned_to=f"assignee{rng.randint(1, 50)}@mail.com",
            )
            self._tasks[t.task_id] = t

    async def get_tasks_by_creator(self, created_by):
        return sorted(
            (t for t in self._tasks.values() if t.task_created_by == created_by),
            key=lambda t: t.task_created_on, reverse=True)

    async def get_task_by_id(self, task_id):
        return self._tasks.get(task_id)

    async def create_new_task(self, add_doc):
        task = add_model(add_doc)
        async with self._lock:
            self._tasks[task.task_id] = task
        return task.task_id

    async def update_task(self, task_id, update_doc):
        async with self._lock:
            task = self._tasks.get(task_id)
            if task is None:
                return False
            apply_update(task, update_doc)
            return True

    async def mark_task_completed(self, task_id):
        async with self._lock:
            task = self._tasks.get(task_id)
            if task is None:
                return False
            task.is_completed = True
            return True

    async def delete_task(self, task_id):
        async with self._lock:
            return self._tasks.pop(task_id, None) is not None

    async def get_yesterdays_due_tasks(self):
        yesterday = format_dt(
            (dt.datetime.now() - dt.timedelta(days=1)).replace(
                hour=0, minute=0, second=0, microsecond=0))
        return [
            t for t in self._tasks.values()
            if t.task_due_date == yesterday and not t.is_completed
        ]

    async def mark_overdue_tasks(self, tasks):
        async with self._lock:
            for doc in tasks:
                task = self._tasks.get(doc.get("taskId", ""))
                if task is not None:
                    task.is_over_due = True


class TasksStoreManager(TasksManager):
    """State-store-backed implementation (TasksStoreManager.cs:9-157).

    ``client`` is the injected AppClient (≙ DaprClient). Publishes
    TaskSaved on create and on reassignment, exactly where the
    reference does (:36, :95-98).

    Update paths EXCEED the reference: the reference's read-modify-
    write has a lost-update race (TasksStoreManager.cs:84-101 does
    get→modify→save with no etag; SURVEY.md §5.2). Here every
    modification is an etag-guarded compare-and-swap with a bounded
    retry-on-conflict loop (``_cas``), so concurrent writers serialize
    instead of silently overwriting each other.
    """

    #: conflict retries before giving up — each retry re-reads, so a
    #: retry only loses if ANOTHER writer progressed (livelock-free)
    CAS_ATTEMPTS = 8

    def __init__(self, client):
        self.client = client

    async def _cas(self, task_id: str, mutate) -> bool:
        """get→mutate→save-if-unchanged. ``mutate(task)`` edits the
        TaskModel in place and may return a zero-arg async callable to
        run after the commit (e.g. a publish — a callable, NOT a
        coroutine, so a conflicting retry discards nothing un-awaited);
        returns False when the key is gone."""
        from tasksrunner.errors import EtagMismatch

        for _ in range(self.CAS_ATTEMPTS):
            item = await self.client.get_state_item(STORE_NAME, task_id)
            if item is None:
                return False
            task = TaskModel.from_json(item.value)
            after_commit = mutate(task)
            try:
                await self.client.save_state(
                    STORE_NAME, task_id, task.to_json(), etag=item.etag)
            except EtagMismatch:
                logger.info("etag conflict on task %s; retrying", task_id)
                continue
            if after_commit is not None:
                await after_commit()
            return True
        raise EtagMismatch(
            f"task {task_id} kept changing under us "
            f"({self.CAS_ATTEMPTS} attempts)")

    async def _publish_task_saved(self, task: TaskModel) -> None:
        # ≙ PublishTaskSavedEvent (TasksStoreManager.cs:151-156)
        logger.info("Publishing task saved event for task %s", task.task_id)
        await self.client.publish_event(PUBSUB_NAME, TOPIC_NAME, task.to_json())

    async def get_tasks_by_creator(self, created_by):
        # ≙ QueryStateAsync w/ EQ filter (TasksStoreManager.cs:56-61)
        result = await self.client.query_state(
            STORE_NAME, {"filter": {"EQ": {"taskCreatedBy": created_by}}})
        tasks = [TaskModel.from_json(r["data"]) for r in result["results"]]
        # ≙ the LINQ order-by-created-desc done app-side (:63-66)
        return sorted(tasks, key=lambda t: t.task_created_on, reverse=True)

    async def get_task_by_id(self, task_id):
        doc = await self.client.get_state(STORE_NAME, task_id)
        return None if doc is None else TaskModel.from_json(doc)

    async def create_new_task(self, add_doc):
        task = add_model(add_doc)
        logger.info("Saving new task with id %s", task.task_id)
        await self.client.save_state(STORE_NAME, task.task_id, task.to_json())
        await self._publish_task_saved(task)
        return task.task_id

    async def update_task(self, task_id, update_doc):
        def mutate(task: TaskModel):
            previous_assignee = task.task_assigned_to  # :92
            apply_update(task, update_doc)
            if previous_assignee != task.task_assigned_to:
                # reassignment republishes the saved event (:95-98) —
                # only after the CAS commits, so a conflicting retry
                # can't emit an event for a version that never landed
                return lambda: self._publish_task_saved(task)
            return None

        return await self._cas(task_id, mutate)

    async def mark_task_completed(self, task_id):
        def mutate(task: TaskModel):
            task.is_completed = True

        return await self._cas(task_id, mutate)

    async def delete_task(self, task_id):
        logger.info("Deleting task with id %s", task_id)
        if await self.get_task_by_id(task_id) is None:
            return False
        await self.client.delete_state(STORE_NAME, task_id)
        return True

    async def get_yesterdays_due_tasks(self):
        # ≙ EQ on the *serialized* due date (TasksStoreManager.cs:104-130,
        # the DateTimeConverter trap): only tasks stored with exactly
        # yesterday-midnight due dates match.
        yesterday = format_dt(
            (dt.datetime.now() - dt.timedelta(days=1)).replace(
                hour=0, minute=0, second=0, microsecond=0))
        result = await self.client.query_state(
            STORE_NAME, {"filter": {"EQ": {"taskDueDate": yesterday}}})
        return [
            t for t in (TaskModel.from_json(r["data"]) for r in result["results"])
            if not t.is_completed
        ]

    async def mark_overdue_tasks(self, tasks):
        # ≙ the per-task sequential SaveStateAsync loop
        # (TasksStoreManager.cs:141-148) — the reference's only hot loop
        for doc in tasks:
            task_id = doc.get("taskId", "")
            if not task_id:
                continue

            def mutate(task: TaskModel):
                logger.info("Marking task %s as overdue", task_id)
                task.is_over_due = True

            await self._cas(task_id, mutate)
