from samples.tasks_tracker.backend_api.app import make_app

__all__ = ["make_app"]
