"""Backend API service — app-id ``tasksmanager-backend-api``.

Route surface ≙ the reference's two controllers:

* ``TasksController`` (Controllers/TasksController.cs:7-76): GET
  ``api/tasks?createdBy=``, GET ``api/tasks/{id}``, POST ``api/tasks``,
  PUT ``api/tasks/{id}``, PUT ``api/tasks/{id}/markcomplete``,
  DELETE ``api/tasks/{id}``
* ``OverdueTasksController`` (Controllers/OverdueTasksController.cs:7-33):
  GET ``api/overduetasks``, POST ``api/overduetasks/markoverdue``

Manager selection ≙ Program.cs DI (:13): ships with the fake manager,
swapped to the store-backed one by config — here the ``TASKS_MANAGER``
env var or ``make_app(manager=...)`` (module 4's swap,
docs/aca/04-aca-dapr-stateapi/index.md:170-192).
"""

from __future__ import annotations

import os

from tasksrunner import App

from samples.tasks_tracker.backend_api.managers import (
    FakeTasksManager,
    TasksManager,
    TasksStoreManager,
)
from samples.tasks_tracker.backend_api.workflows import register_workflows

APP_ID = "tasksmanager-backend-api"


def make_app(manager: str | TasksManager | None = None) -> App:
    app = App(APP_ID)

    mode = manager if isinstance(manager, str) else None
    if mode is None:
        mode = os.environ.get("TASKS_MANAGER", "store")

    @app.on_startup
    async def init_manager():
        if isinstance(manager, TasksManager):
            app.state["tasks"] = manager
        elif mode == "fake":
            app.state["tasks"] = FakeTasksManager()
        else:
            app.state["tasks"] = TasksStoreManager(app.client)

    def tasks() -> TasksManager:
        return app.state["tasks"]

    # -- TasksController -------------------------------------------------

    @app.get("/api/tasks")
    async def get_tasks(req):
        created_by = req.query.get("createdBy", "")
        if not created_by:
            return 400, {"error": "createdBy query parameter is required"}
        return [t.to_json() for t in await tasks().get_tasks_by_creator(created_by)]

    @app.get("/api/tasks/{task_id}")
    async def get_task(req):
        task = await tasks().get_task_by_id(req.path_params["task_id"])
        if task is None:
            return 404
        return task.to_json()

    @app.post("/api/tasks")
    async def create_task(req):
        doc = req.json() or {}
        if not doc.get("taskName") or not doc.get("taskCreatedBy"):
            return 400, {"error": "taskName and taskCreatedBy are required"}
        task_id = await tasks().create_new_task(doc)
        return 201, {"taskId": task_id}

    @app.put("/api/tasks/{task_id}")
    async def update_task(req):
        ok = await tasks().update_task(req.path_params["task_id"], req.json() or {})
        return 200 if ok else 404

    @app.put("/api/tasks/{task_id}/markcomplete")
    async def mark_complete(req):
        ok = await tasks().mark_task_completed(req.path_params["task_id"])
        return 200 if ok else 404

    @app.delete("/api/tasks/{task_id}")
    async def delete_task(req):
        ok = await tasks().delete_task(req.path_params["task_id"])
        return 200 if ok else 404

    # -- OverdueTasksController ------------------------------------------

    @app.get("/api/overduetasks")
    async def get_overdue(req):
        return [t.to_json() for t in await tasks().get_yesterdays_due_tasks()]

    @app.post("/api/overduetasks/markoverdue")
    async def mark_overdue(req):
        await tasks().mark_overdue_tasks(req.json() or [])
        return 200

    # -- durable workflows (module 21) -----------------------------------
    # registration is unconditional and cheap: the engine is lazy, and
    # with TASKSRUNNER_WORKFLOWS unset the runtime never hosts it
    register_workflows(app, tasks)

    return app
