"""Task DTOs for the backend API service.

Field-for-field parity with the reference's models
(TasksTracker.TasksManager.Backend.Api/Models/TaskModel.cs:3-29):
TaskModel (8 props), TaskAddModel (4), TaskUpdateModel (4). JSON names
use the same camelCase the reference serializes.

Datetime contract: all dates serialize with ``DATETIME_FORMAT`` — the
role the reference's DateTimeConverter plays
(Utilities/DateTimeConverter.cs:6-30): state queries filter on the
*serialized* string, so writer and query must agree on one format.
"""

from __future__ import annotations

import datetime as dt
import uuid
from dataclasses import dataclass, field
from typing import Any

DATETIME_FORMAT = "%Y-%m-%dT%H:%M:%S"


def format_dt(value: dt.datetime) -> str:
    return value.strftime(DATETIME_FORMAT)


def parse_dt(text: str) -> dt.datetime:
    # accept a few common forms but always *emit* DATETIME_FORMAT
    for fmt in (DATETIME_FORMAT, "%Y-%m-%d", "%Y-%m-%dT%H:%M:%S.%f"):
        try:
            return dt.datetime.strptime(text, fmt)
        except ValueError:
            continue
    try:
        return dt.datetime.fromisoformat(text)
    except ValueError:
        from tasksrunner.errors import ValidationError
        raise ValidationError(f"unparseable date {text!r}") from None


@dataclass
class TaskModel:
    task_id: str = field(default_factory=lambda: str(uuid.uuid4()))
    task_name: str = ""
    task_created_by: str = ""
    task_created_on: str = field(default_factory=lambda: format_dt(dt.datetime.now()))
    task_due_date: str = ""
    task_assigned_to: str = ""
    is_completed: bool = False
    is_over_due: bool = False

    def to_json(self) -> dict[str, Any]:
        return {
            "taskId": self.task_id,
            "taskName": self.task_name,
            "taskCreatedBy": self.task_created_by,
            "taskCreatedOn": self.task_created_on,
            "taskDueDate": self.task_due_date,
            "taskAssignedTo": self.task_assigned_to,
            "isCompleted": self.is_completed,
            "isOverDue": self.is_over_due,
        }

    @classmethod
    def from_json(cls, doc: dict[str, Any]) -> "TaskModel":
        return cls(
            task_id=doc.get("taskId") or str(uuid.uuid4()),
            task_name=doc.get("taskName", ""),
            task_created_by=doc.get("taskCreatedBy", ""),
            task_created_on=doc.get("taskCreatedOn", ""),
            task_due_date=doc.get("taskDueDate", ""),
            task_assigned_to=doc.get("taskAssignedTo", ""),
            is_completed=bool(doc.get("isCompleted", False)),
            is_over_due=bool(doc.get("isOverDue", False)),
        )


def add_model(doc: dict[str, Any]) -> TaskModel:
    """≙ TaskAddModel → new TaskModel (TasksStoreManager.CreateNewTask)."""
    due = doc.get("taskDueDate", "")
    if due:
        due = format_dt(parse_dt(due))
    return TaskModel(
        task_name=doc.get("taskName", ""),
        task_created_by=doc.get("taskCreatedBy", ""),
        task_due_date=due,
        task_assigned_to=doc.get("taskAssignedTo", ""),
    )


def apply_update(task: TaskModel, doc: dict[str, Any]) -> TaskModel:
    """≙ TaskUpdateModel applied in UpdateTask (TasksStoreManager.cs:84-101)."""
    if "taskName" in doc:
        task.task_name = doc["taskName"]
    if "taskDueDate" in doc and doc["taskDueDate"]:
        task.task_due_date = format_dt(parse_dt(doc["taskDueDate"]))
    if "taskAssignedTo" in doc:
        task.task_assigned_to = doc["taskAssignedTo"]
    return task
