"""Durable workflow scenarios for the tasks tracker (docs module 21).

Three shapes, one per guarantee the engine adds over bare handlers:

* ``checkout`` — the saga: reserve each line item, charge the card,
  send the confirmation; any late failure runs the registered
  compensations in reverse order, exactly once, even across a
  ``kill -9`` of the owning replica.
* ``overdue-escalation`` — reminder-driven: a durable timer wakes the
  instance per escalation level, so the nag survives host loss and
  fires on whichever replica adopts the instance.
* ``overdue-sweep`` — fan-out/fan-in: one collection activity, then a
  per-task marking activity for every due task, joined by
  ``ctx.when_all``.

Orchestrators are replayed, so they touch the world ONLY through
``ctx.*`` (the ``workflow-determinism`` lint rule enforces this);
every effect lives in an activity. Effects staged with
``actx.stage_effect`` commit atomically with the history event that
records the activity — exactly-once. Manager calls from activities are
at-least-once (the body may re-run after a crash), which is fine here
because marking a task overdue is idempotent.
"""

from __future__ import annotations

from tasksrunner.resiliency.policy import RetrySpec

#: the card limit the sample's pretend payment gateway enforces —
#: start a checkout above it to watch the compensations run
CARD_LIMIT = 500.0


def register_workflows(app, tasks) -> None:
    """Attach the scenario workflows to ``app``; ``tasks`` is the
    zero-arg accessor returning the active ``TasksManager``."""

    # -- checkout: the compensation saga ------------------------------

    @app.workflow("checkout")
    async def checkout(ctx, order):
        order = dict(order or {})
        order_id = order.get("orderId") or ctx.uuid4()
        for item in order.get("items", []):
            stock = await ctx.call_activity(
                "reserve-stock", {"orderId": order_id, "item": item})
            ctx.register_compensation("release-stock", stock)
        receipt = await ctx.call_activity(
            "charge-card",
            {"orderId": order_id, "amount": order.get("amount", 0)})
        ctx.register_compensation("refund-card", receipt)
        await ctx.call_activity(
            "send-confirmation",
            {"orderId": order_id, "placedAt": ctx.now()})
        return {"orderId": order_id, "receipt": receipt}

    @app.activity("reserve-stock")
    async def reserve_stock(actx, data):
        actx.stage_effect(
            f"checkout||{data['orderId']}||reserved||{data['item']}", data)
        return data

    @app.activity("release-stock")
    async def release_stock(actx, data):
        # the undo is a staged DELETE of the reservation — committed
        # atomically with the `compensated` history event, so a crash
        # between compensations never half-releases
        actx.stage_effect(
            f"checkout||{data['orderId']}||reserved||{data['item']}",
            operation="delete")
        return data["item"]

    @app.activity("charge-card",
                  retry=RetrySpec(policy="exponential", duration=0.05,
                                  max_retries=3),
                  timeout=10.0)
    async def charge_card(actx, data):
        amount = float(data.get("amount") or 0)
        if amount > CARD_LIMIT:
            raise RuntimeError(
                f"card declined: {amount} exceeds limit {CARD_LIMIT}")
        receipt = {"orderId": data["orderId"], "amount": amount,
                   "attempt": actx.attempt}
        actx.stage_effect(f"checkout||{data['orderId']}||charge", receipt)
        return receipt

    @app.activity("refund-card")
    async def refund_card(actx, receipt):
        actx.stage_effect(f"checkout||{receipt['orderId']}||charge",
                          operation="delete")
        actx.stage_effect(f"checkout||{receipt['orderId']}||refund", receipt)
        return receipt["orderId"]

    @app.activity("send-confirmation")
    async def send_confirmation(actx, data):
        actx.stage_effect(
            f"checkout||{data['orderId']}||confirmation", data)
        return data["orderId"]

    # -- overdue escalation: durable timers ---------------------------

    @app.workflow("overdue-escalation")
    async def overdue_escalation(ctx, req):
        req = dict(req or {})
        task_id = req["taskId"]
        interval = float(req.get("intervalSeconds", 3600.0))
        levels = int(req.get("maxLevels", 3))
        for level in range(1, levels + 1):
            await ctx.sleep(interval)
            task = await ctx.call_activity("check-task", task_id)
            if task is None or task.get("isCompleted"):
                return {"taskId": task_id, "outcome": "completed",
                        "nags": level - 1}
            await ctx.call_activity(
                "escalate", {"taskId": task_id, "level": level,
                             "at": ctx.now()})
        await ctx.call_activity("mark-task-overdue", {"taskId": task_id})
        return {"taskId": task_id, "outcome": "overdue", "nags": levels}

    @app.activity("check-task")
    async def check_task(actx, task_id):
        task = await tasks().get_task_by_id(task_id)
        return None if task is None else task.to_json()

    @app.activity("escalate")
    async def escalate(actx, data):
        # the audit trail is the exactly-once part; a real deployment
        # would also publish a nag notification here (at-least-once)
        actx.stage_effect(
            f"escalation||{data['taskId']}||{data['level']}", data)
        return data["level"]

    @app.activity("mark-task-overdue")
    async def mark_task_overdue(actx, doc):
        # idempotent by construction: marking an overdue task overdue
        # again is a no-op, so at-least-once execution is harmless
        await tasks().mark_overdue_tasks([doc])
        actx.stage_effect(f"overdue||{doc['taskId']}", doc)
        return doc["taskId"]

    # -- overdue sweep: fan-out/fan-in --------------------------------

    @app.workflow("overdue-sweep")
    async def overdue_sweep(ctx, _req):
        due = await ctx.call_activity("collect-due-tasks", None)
        marked = await ctx.when_all(
            [ctx.call_activity("mark-task-overdue", doc) for doc in due])
        return {"swept": len(marked), "taskIds": marked}

    @app.activity("collect-due-tasks")
    async def collect_due_tasks(actx, _data):
        return [t.to_json() for t in await tasks().get_yesterdays_due_tasks()]
