"""Processor service — app-id ``tasksmanager-backend-processor``.

The event-driven backend ≙ TasksTracker.Processor.Backend.Svc, three
controllers:

* ``TasksNotifierController`` (Controllers/TasksNotifierController.cs:8-33):
  subscribes to ``tasksavedtopic`` on both the cloud pubsub
  (``dapr-pubsub-servicebus``) and the local one (``taskspubsub``),
  route ``POST api/tasksnotifier/tasksaved``; sends the assignee an
  email through the ``sendgrid`` output binding gated by
  ``SendGrid:IntegrationEnabled`` config (module-6 version,
  docs/aca/06-aca-dapr-bindingsapi/TasksNotifierController.cs:38-57)
* ``ScheduledTasksManagerController`` (:7-47): cron component
  ``ScheduledTasksManager`` POSTs the route named after it; fetches
  ``api/overduetasks`` via invoke :28, filters dueDate < today :32-38,
  posts ``markoverdue`` :44
* ``ExternalTasksProcessorController`` (:7-54): storage-queue input
  binding routes to ``POST /externaltasksprocessor/process``; assigns
  id/createdOn :29-30, saves via invoke :33, archives to the
  ``externaltasksblobstore`` output binding with blobName "{id}.json"
  :38-43
"""

from __future__ import annotations

import asyncio
import datetime as dt
import logging
import os

from tasksrunner import App

logger = logging.getLogger(__name__)

APP_ID = "tasksmanager-backend-processor"
BACKEND_APP_ID = "tasksmanager-backend-api"
CLOUD_PUBSUB = "dapr-pubsub-servicebus"  # TasksNotifierController.cs:23
LOCAL_PUBSUB = "taskspubsub"             # :25 (Redis slot locally)
TOPIC = "tasksavedtopic"
SENDGRID_BINDING = "sendgrid"            # docs module 6 :13
BLOB_BINDING = "externaltasksblobstore"  # ExternalTasksProcessorController.cs:13
DATETIME_FORMAT = "%Y-%m-%dT%H:%M:%S"


def make_app(*, sendgrid_enabled: bool | None = None) -> App:
    app = App(APP_ID)
    if sendgrid_enabled is None:
        # ≙ config SendGrid:IntegrationEnabled (processor-backend-service
        # .bicep:148-151, env SendGrid__IntegrationEnabled)
        sendgrid_enabled = os.environ.get(
            "SENDGRID__INTEGRATIONENABLED", "true").lower() == "true"
    app.state["sendgrid_enabled"] = sendgrid_enabled
    app.state["notified"] = []  # observable record of handled events

    # -- TasksNotifierController -----------------------------------------

    # ≙ the reference's load-test posture: with the integration off its
    # controller sleeps 1 s per message ("Introduce artificial delay to
    # slow down message processing", docs/aca/06-aca-dapr-bindingsapi/
    # TasksNotifierController.cs:60-63) — that simulated work is what
    # makes consumers the bottleneck so the module-9 flood has
    # something to scale. Overridable for fast tests.
    try:
        sim_work_s = float(os.environ.get(
            "SENDGRID__SIMULATED_WORK_MS", "1000")) / 1000.0
    except ValueError:
        # a tuning knob must not crash-loop the replica: fall back to
        # the reference's 1 s and say so
        logger.warning("SENDGRID__SIMULATED_WORK_MS=%r is not a number; "
                       "using 1000 ms",
                       os.environ.get("SENDGRID__SIMULATED_WORK_MS"))
        sim_work_s = 1.0

    async def _task_saved(req):
        task = req.data or {}
        logger.info("Started processing message with task name '%s'",
                    task.get("taskName"))
        app.state["notified"].append(task)
        if app.state["sendgrid_enabled"]:
            await app.client.invoke_binding(
                SENDGRID_BINDING, "create",
                f"<p>Task <b>{task.get('taskName', '')}</b> is assigned to you.</p>",
                {
                    "emailTo": task.get("taskAssignedTo", ""),
                    "emailToName": task.get("taskAssignedTo", ""),
                    "subject": "Tasks assigned to you",
                },
            )
        elif sim_work_s > 0:
            logger.info("Simulate slow processing for email with subject "
                        "'Tasks assigned to you' to: '%s'",
                        task.get("taskAssignedTo", ""))
            await asyncio.sleep(sim_work_s)
        return 200

    # both [Topic] attributes stack on one action (cloud + local slots)
    app.subscribe(CLOUD_PUBSUB, TOPIC, route="/api/tasksnotifier/tasksaved")(_task_saved)
    app.subscribe(LOCAL_PUBSUB, TOPIC, route="/api/tasksnotifier/tasksaved")(_task_saved)

    # -- ScheduledTasksManagerController ---------------------------------

    @app.binding("ScheduledTasksManager")
    async def check_overdue_tasks_job(req):
        run_at = dt.datetime.now()
        logger.info("ScheduledTasksManager executed at %s", run_at)
        overdue = await app.client.invoke_json(
            BACKEND_APP_ID, "api/overduetasks", http_method="GET")
        # filter runAt.Date > dueDate.Date in-process (:32-38)
        to_mark = []
        for task in overdue:
            try:
                due = dt.datetime.strptime(task.get("taskDueDate", ""),
                                           DATETIME_FORMAT)
            except ValueError:
                continue
            if run_at.date() > due.date():
                to_mark.append(task)
        if to_mark:
            logger.info("Marking %d tasks overdue", len(to_mark))
            resp = await app.client.invoke_method(
                BACKEND_APP_ID, "api/overduetasks/markoverdue",
                http_method="POST", data=to_mark)
            resp.raise_for_status()
        return 200

    # -- ExternalTasksProcessorController --------------------------------

    @app.binding("externaltasksmanager", route="/externaltasksprocessor/process")
    async def process_external_task(req):
        task = req.data or {}
        # assign server-side identity (:29-30)
        import uuid
        task["taskId"] = str(uuid.uuid4())
        task["taskCreatedOn"] = dt.datetime.now().strftime(DATETIME_FORMAT)
        resp = await app.client.invoke_method(
            BACKEND_APP_ID, "api/tasks", http_method="POST", data=task)
        resp.raise_for_status()
        created = resp.json()
        # archive under the *stored* id so the blob correlates with the
        # state store (the API, like the reference's, assigns its own id)
        task["taskId"] = created["taskId"]
        await app.client.invoke_binding(
            BLOB_BINDING, "create", task,
            {"blobName": f"{created['taskId']}.json"})
        return 200

    return app
