from samples.tasks_tracker.processor.app import make_app

__all__ = ["make_app"]
