# ≙ /root/reference/Makefile:1-13 (docs build/serve glue) plus the
# local dev workflow targets.
.PHONY: test lint lint-program lint-dataflow lint-interleave verify lint-changed lint-metrics soak bench bench-state bench-shard bench-hist bench-trace bench-overload bench-actors bench-workflows bench-repl bench-reshard bench-mesh bench-ml-serve chaos sweep-flash run validate docs-serve docs-build clean

test: lint lint-program lint-dataflow lint-interleave
	python -m pytest tests/ -q

# tasklint: AST enforcement of the runtime's invariants — no blocking
# calls on the event loop, declared metric names, env_flag for every
# boolean knob, errors.py taxonomy on sidecar-facing paths
# (docs/modules/17-static-analysis.md)
lint:
	python -m tasksrunner.analysis

# whole-program phase only: call-graph, lock-graph, thread-boundary,
# and route-conformance rules over the full package (tree-digest
# cached, so warm runs are near-free)
lint-program:
	python -m tasksrunner.analysis --rules transitive-blocking,lock-order-cycle,held-lock-across-await,thread-shared-state,route-conformance

# dataflow phase only: CFG-based secret-taint, resource-lifetime,
# cancellation-safety, and exception-flow analysis over the full
# package (tree-digest cached like the program phase)
lint-dataflow:
	python -m tasksrunner.analysis --rules secret-taint,resource-lifetime,cancellation-safety,exception-flow

# interleave phase only: atomic-section check-then-act windows and
# fenced-lane etag/epoch discipline over the full package
# (tree-digest cached like the program phase)
lint-interleave:
	python -m tasksrunner.analysis --rules interleave-check-act,fenced-etag-origin,fenced-epoch-monotone

# protocol kernels under exhaustive interleavings with crash points:
# lease takeover + epoch fence, quorum append + resync ladder,
# workflow turn commit — plus the seeded-bug self-test
verify:
	python -m tasksrunner.cli verify

# fast pre-commit loop: per-file phase on the git delta vs main; the
# program and dataflow phases still cover the whole tree
lint-changed:
	python -m tasksrunner.analysis --changed

# back-compat alias: the metric-name check is now the tasklint
# `metric-names` rule
lint-metrics:
	python -m tasksrunner.analysis --rules metric-names

soak:
	TASKSRUNNER_SOAK=1 python -m pytest tests/test_soak.py -q
	python -m pytest tests/ -q -m slow

bench:
	python bench.py

# state-store section only: group-commit write queue vs the
# one-commit-per-call path, plus the read cache — seconds, not minutes
bench-state:
	python bench.py --state-bench

# sharded state plane: write-heavy ops/s swept over shards {1,2,4,8};
# the speedup needs cores (N writer threads) — on a 1-core host this
# measures the facade's overhead, not the parallel-commit gain
bench-shard:
	python bench.py --shard-bench

# histogram hot-path cost: histograms-on vs -off on the write-heavy
# state path and the publish/deliver path (must stay < 3%)
bench-hist:
	python bench.py --hist-bench

# causal-tracing hot-path cost: span recorder on vs off (the
# TASKSRUNNER_TRACE_DB-unset default) on the state-write,
# publish/deliver, and actor-turn paths (<3% on, ~0% off), plus the
# flight-recorder ring-append cost vs its disabled one-if path
bench-trace:
	python bench.py --trace-bench

# overload protection: the drill test (shed -> scale out -> recover,
# zero lost acks), then the bench section — admission-gate overhead on
# the ingress path (<1% when off) + the drill's measured trajectory
bench-overload:
	python -m pytest tests/test_overload_drill.py -q -m "not slow"
	python bench.py --overload-bench

# virtual actors: the test suite (fencing, reminders, the seeded
# crashEveryN failover drill), then the bench section — turn
# throughput, failover time, zero lost acked turns, and the gate-off
# sidecar ingress overhead (<1% when TASKSRUNNER_ACTORS is unset)
bench-actors:
	python -m pytest tests/test_actors.py -q -m "not slow"
	python bench.py --actor-bench

# durable workflows: the test suite (replay determinism, sagas, the
# chaos + kill -9 recovery drills), then the bench section — saga
# throughput, replay-recovery latency after an owner crash, and the
# history-append overhead of a workflow step vs a bare actor turn
bench-workflows:
	python -m pytest tests/test_workflows.py -q -m "not slow"
	python bench.py --workflow-bench

# replicated state plane: the replication test matrix (record stream,
# fencing, resync, mesh transport, kill -9 drill), then the RF {1,2,3}
# write-overhead sweep + leader-crash failover drill (zero lost acked
# writes at RF 2)
bench-repl:
	python -m pytest tests/test_replication.py -q -m "not slow"
	python bench.py --replication-bench

# elastic placement: the epoch-fence/migration test suite, then the
# live-split-under-load drill — steady vs during-migration p99 (within
# 2x), zero lost acked writes across the flip, and the hot-key-storm
# detection knee
bench-reshard:
	python -m pytest tests/test_placement.py -q -m "not slow"
	python bench.py --reshard-bench

# mesh fast lane: the transport test matrix (codec negotiation, legacy
# interop, coalescing, prewarm, condemnation), then the per-lever
# ladder — JSON vs binary headers, per-frame drain vs coalesced
# writes, cold vs pre-warmed dial, uvloop when installed
bench-mesh:
	python -m pytest tests/test_mesh_fastpath.py tests/test_mesh.py -q -m "not slow"
	python bench.py --mesh-bench

# ML serving plane: the batcher test matrix (flush discipline, bucket
# jit cache, error isolation, shed, warmup backoff), then continuous
# batching vs batch-of-one through the real service plus the
# admission-protected flood drill
bench-ml-serve:
	JAX_PLATFORMS=cpu python -m pytest tests/test_ml_batching.py -q -m "not slow"
	JAX_PLATFORMS=cpu python bench.py --ml-serve-bench

# chaos verification: the deterministic fault-injection harness, the
# faulty-broker convergence soak, and the proof that the disabled gate
# costs <1% on the write-heavy state path
chaos:
	python -m pytest tests/test_chaos.py -q
	python -m pytest "tests/test_soak.py::test_tasks_pipeline_converges_despite_faulty_broker" -q
	python bench.py --chaos-bench

sweep-flash:
	python scripts/sweep_flash_bwd.py

run:
	python -m tasksrunner run run.yaml

validate:
	python -m tasksrunner deploy validate samples/tasks_tracker/environment.yaml
	python -m tasksrunner components samples/tasks_tracker/components

docs-serve:
	mkdocs serve

docs-build:
	mkdocs build --strict

clean:
	rm -rf .tasksrunner samples/tasks_tracker/.tasksrunner
	find . -name '__pycache__' -type d -exec rm -rf {} +
